"""Auto-recovery actions for health-auditor findings.

``rank_divergence`` used to be evidence-only; here it becomes a repair:
every rank participates (the collective schedule must stay SPMD —
identical host-plane allgathers on all ranks), rank 0 serializes its
model state, diverged ranks rebuild from it after hash verification,
and the per-rank score carries are fixed up by subtracting the rank's
OWN old trees' contributions and adding the repaired trees' — the score
rows a rank owns were trained with its own (possibly diverged) routing,
so the rank-local old tree is exactly what must come back out.

The fix-up dispatches are collective-free elementwise replays (the same
ops rollback_one_iter uses), and the replayed index set is the
allgathered UNION of per-rank diffs, so every rank issues the same
number of dispatches regardless of which rank diverged.

Post-repair the hashes are re-allgathered: equal means repaired; a
persistent mismatch (e.g. the ``LIGHTGBM_TPU_HEALTH_FAULT_RANK`` salt,
which taints the digest, not the model) reports ``repaired: false`` and
the auditor disables further resync attempts for the run instead of
thrashing.
"""
from __future__ import annotations

import base64
import io
import json
from typing import Dict, List

import numpy as np

from ..obs.health import model_state_hash
from ..utils import log
from .state import trees_from_arrays, trees_to_arrays


def serialize_models_blob(models) -> str:
    """Model list -> ascii blob (npz arrays + JSON meta, base64) small
    enough to ride the JSON host-plane allgather."""
    meta, arrays = trees_to_arrays(models)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return json.dumps({"meta": meta,
                       "npz": base64.b64encode(buf.getvalue())
                       .decode("ascii")})


def deserialize_models_blob(blob: str):
    obj = json.loads(blob)
    arrays = np.load(io.BytesIO(base64.b64decode(obj["npz"])),
                     allow_pickle=False)
    return trees_from_arrays(obj["meta"], arrays)


def _trees_differ(a, b) -> bool:
    """Same fields the health hash covers — a digest mismatch must map
    to at least one differing tree."""
    for field, dt in (("leaf_value", np.float64),
                      ("split_feature", np.int32),
                      ("threshold", np.float64),
                      ("threshold_bin", np.int32),
                      ("decision_type", np.int32)):
        av = np.asarray(getattr(a, field), dtype=dt)
        bv = np.asarray(getattr(b, field), dtype=dt)
        if av.shape != bv.shape or not np.array_equal(av, bv):
            return True
    return False


def _replay_tree(gbdt, idx: int, dt, scale: float) -> None:
    tid = idx % gbdt.num_tree_per_iteration
    gbdt.scores = gbdt._add_tree_to_score(
        gbdt.scores, gbdt._train_bins_replay(), dt, tid, scale=scale,
        bundle=gbdt._train_bundle())
    for vi in range(len(gbdt.valid_scores)):
        gbdt.valid_scores[vi] = gbdt._add_tree_to_score(
            gbdt.valid_scores[vi], gbdt.valid_bins[vi], dt, tid,
            scale=scale, bundle=gbdt._valid_bundle(vi))


def resync_from_rank0(gbdt, it: int, per_rank: List[Dict]) -> bool:
    """Repair a detected divergence by re-syncing from rank 0's
    hash-verified model state. SPMD: every rank calls this from the same
    audit round. Returns True when the post-repair hashes agree."""
    from ..obs.registry import allgather_json
    tel = gbdt.telemetry
    if len(per_rank) <= 1:
        return True
    rank = tel.rank
    ref_hash = next((r["hash"] for r in per_rank
                     if int(r["rank"]) == 0), None)
    # rank 0 ships its serialization; everyone else ships a placeholder
    # (the allgather itself is the broadcast — same one collective the
    # auditor already rides)
    blob = serialize_models_blob(gbdt.models) if rank == 0 else None
    payloads = allgather_json({"blob": blob})
    src = payloads[0].get("blob") if payloads else None
    ok = False
    replaced = 0
    if src is None:
        log.warning("divergence resync aborted: rank 0 sent no model")
        union: List[int] = []
    else:
        new_models = deserialize_models_blob(src)
        verified = model_state_hash(new_models, rank=-1) == ref_hash
        if not verified:
            log.warning("divergence resync: rank 0's serialization does "
                        "not reproduce its reported hash; model left "
                        "untouched")
        if len(new_models) != len(gbdt.models) or not verified:
            local_diff: List[int] = []
        else:
            local_diff = [i for i in range(len(new_models))
                          if _trees_differ(gbdt.models[i], new_models[i])]
        # the union keeps the dispatch count identical on every rank —
        # healthy ranks replay (subtract + re-add) their own identical
        # tree, diverged ranks swap in the repaired one
        gathered = allgather_json({"diff": local_diff,
                                   "usable": bool(verified
                                                  and len(new_models)
                                                  == len(gbdt.models))})
        if all(g.get("usable") for g in gathered):
            union = sorted({i for g in gathered for i in g["diff"]})
        else:
            union = []
        for idx in union:
            _replay_tree(gbdt, idx, gbdt.device_trees[idx], -1.0)
            if idx in local_diff:
                gbdt.models[idx] = new_models[idx]
                gbdt.device_trees[idx] = \
                    gbdt._device_tree_for_resume(new_models[idx])
                replaced += 1
            _replay_tree(gbdt, idx, gbdt.device_trees[idx], 1.0)
    # post-repair verification: the salted fault keeps mismatching here
    # by design — that is the "repair did not converge" signal
    post = allgather_json(
        {"hash": model_state_hash(gbdt.models, rank=rank)})
    ok = len({p["hash"] for p in post}) == 1
    tel.inc("health.resync")
    tel.event("recovery", action="resync", iteration=it, repaired=ok,
              replaced_trees=replaced,
              union=len(union),
              hashes={str(i): p["hash"][:16]
                      for i, p in enumerate(post)})
    if ok:
        log.warning("rank divergence at iteration %d repaired from "
                    "rank 0 (%d trees replaced on rank %d)", it,
                    replaced, rank)
    return ok


def inject_divergence(gbdt, it: int) -> None:
    """Chaos hook (faults.py ``diverge``): perturb the newest grown tree
    on this rank — model AND this rank's score rows together, keeping
    the rank-internal invariant a real silent-corruption event would
    (the rank's scores reflect its own model), which is exactly the
    state resync_from_rank0 knows how to repair."""
    import jax.numpy as jnp
    target = None
    for idx in range(len(gbdt.models) - 1, -1, -1):
        if gbdt.models[idx].num_leaves > 1:
            target = idx
            break
    if target is None:
        log.warning("diverge fault: no grown tree to corrupt yet")
        return
    ht = gbdt.models[target]
    dt = gbdt.device_trees[target]
    _replay_tree(gbdt, target, dt, -1.0)
    ht.leaf_value = np.asarray(ht.leaf_value, np.float64) + 1e-3
    dt.leaf_value = jnp.asarray(ht.leaf_value, jnp.float32)
    _replay_tree(gbdt, target, dt, 1.0)
    log.warning("fault injection: diverged rank %d at iteration %d "
                "(tree %d leaf values perturbed)", gbdt.telemetry.rank,
                it, target)
    gbdt.telemetry.event("fault_injected", kind="diverge", iteration=it,
                         tree=target)
