"""Capture/restore of the GBDT driver's complete resumable state.

The checkpoint snapshot is everything the driver needs to continue a
run BIT-IDENTICALLY to one that was never interrupted:

- the materialized model (every HostTree's arrays, float64 — binary
  exact, no text round-trip);
- the train/valid score carries at the capture boundary (f32 device
  buffers pulled to host; restoring them by value is what makes resume
  exact — replaying trees would re-accumulate in a different f32 order);
- the bagging block-LCG stream positions, the live in-bag weight
  vector, the feature-fraction LCG position, and the boosting-mode
  extras (GOSS's MT19937, DART's drop stream + tree weights);
- early-stopping state: the driver-level best dicts (CLI loop) plus,
  via the engine's extra-state hook, the callback closures' best lists
  (engine loop; the megastep's device early-stop carry is synthesized
  back from those — see :func:`synthesize_es_carry`);
- telemetry counters, so dashboards survive a respawn without resets.

Capture runs at a drain boundary (the one host sync point the fast path
has), so the score fetch rides the sync that already happened; the
actual file I/O is the background writer's (checkpoint.py).

Multi-process: each rank captures its OWN row block of the sharded
train-score carry (``MultiProcLayout.local_block``) and restores it
with ``shard_local_cols`` — checkpoints are per-rank files selected as
a hash-consistent set by the launcher.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..models.tree import HostTree
from ..obs.health import model_state_hash
from ..utils import log

# per-tree numeric arrays saved verbatim (HostTree field -> npz entry)
_TREE_FIELDS = ("split_feature", "threshold", "threshold_bin",
                "decision_type", "left_child", "right_child", "split_gain",
                "internal_value", "internal_weight", "internal_count",
                "leaf_value", "leaf_weight", "leaf_count", "leaf_depth")

_SANITY_KEYS = ("objective", "num_class", "tree_learner", "num_leaves",
                "learning_rate", "max_bin", "bagging_seed", "bagging_freq",
                "bagging_fraction", "feature_fraction",
                "feature_fraction_seed", "seed")


def _fetch_rows(gbdt, arr) -> np.ndarray:
    """Device score matrix -> host numpy; under multi-process a sharded
    carry yields this rank's [k, block] column block."""
    mp = getattr(gbdt, "mp", None)
    if mp is not None and not getattr(arr, "is_fully_addressable", True):
        return np.asarray(mp.local_block(arr, axis=1))
    return np.asarray(arr)


def trees_to_arrays(models: List[HostTree]) -> Tuple[List[Dict], Dict]:
    """(per-tree JSON meta, npz arrays) for a model list — shared by the
    checkpoint capture and the recovery re-sync blob."""
    meta: List[Dict] = []
    arrays: Dict[str, np.ndarray] = {}
    for i, ht in enumerate(models):
        m: Dict[str, Any] = {
            "num_leaves": int(ht.num_leaves),
            "shrinkage": float(ht.shrinkage),
            "cat_boundaries": [int(x) for x in ht.cat_boundaries],
            "cat_threshold": [int(x) for x in ht.cat_threshold],
        }
        if ht.is_linear:
            m["is_linear"] = True
            m["leaf_const"] = [float(x) for x in np.asarray(ht.leaf_const)]
            m["leaf_features"] = [[int(f) for f in fs]
                                  for fs in ht.leaf_features]
            m["leaf_coeff"] = [[float(c) for c in cs]
                               for cs in ht.leaf_coeff]
        meta.append(m)
        for f in _TREE_FIELDS:
            arrays[f"t{i}_{f}"] = np.array(getattr(ht, f))
    return meta, arrays


def trees_from_arrays(meta: List[Dict], arrays) -> List[HostTree]:
    models: List[HostTree] = []
    for i, m in enumerate(meta):
        ht = HostTree(int(m["num_leaves"]),
                      shrinkage=float(m.get("shrinkage", 1.0)))
        for f in _TREE_FIELDS:
            setattr(ht, f, np.array(arrays[f"t{i}_{f}"]))
        ht.cat_boundaries = [int(x) for x in m.get("cat_boundaries", [0])]
        ht.cat_threshold = [int(x) for x in m.get("cat_threshold", [])]
        if m.get("is_linear"):
            ht.is_linear = True
            ht.leaf_const = np.asarray(m.get("leaf_const", []), np.float64)
            ht.leaf_features = [list(fs) for fs in m.get("leaf_features",
                                                         [])]
            ht.leaf_coeff = [list(cs) for cs in m.get("leaf_coeff", [])]
        models.append(ht)
    return models


# ------------------------------------------------------------- capture
def capture(gbdt) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Snapshot the driver's resumable state as (JSON payload, arrays).
    Called at a consistency boundary (pending queue drained)."""
    import jax
    tel = gbdt.telemetry
    cfg = gbdt.config
    k = gbdt.num_tree_per_iteration
    meta, arrays = trees_to_arrays(gbdt.models)
    arrays["scores"] = _fetch_rows(gbdt, gbdt.scores)
    for vi, vs in enumerate(gbdt.valid_scores):
        arrays[f"vscore{vi}"] = _fetch_rows(gbdt, vs)
    arrays["bag_stream_state"] = np.array(gbdt.bag_streams.state)
    bag = {"is_bagging": bool(gbdt.is_bagging),
           "bag_cnt": int(gbdt.bag_cnt)}
    if gbdt.is_bagging:
        host_w = getattr(gbdt, "_bag_weight_host", None)
        arrays["bag_weight"] = (np.asarray(host_w) if host_w is not None
                                else np.asarray(gbdt.bag_weight))
    cache = getattr(gbdt, "_bag_round_cache", None) or {}
    bag["cache_keys"] = sorted(int(key) for key in cache)
    for j, key in enumerate(bag["cache_keys"]):
        arrays[f"bag_cache{j}"] = np.asarray(cache[key], bool)
    if getattr(gbdt, "use_screening", False) \
            and getattr(gbdt, "_gain_ema_dev", None) is not None:
        # EMA-FS gain-screening state (tpu_gain_screening): the
        # per-feature gain EMA is part of the resumable training state —
        # without it a resumed run would re-warm the mask and diverge
        # from the uninterrupted run's feature screening
        arrays["gain_ema"] = np.asarray(gbdt._gain_ema_dev, np.float32)
    extra_payload, extra_arrays = gbdt._capture_boosting_extra()
    arrays.update(extra_arrays)
    extra_cb = getattr(gbdt, "_ckpt_extra", None)
    engine_extra: Dict[str, Any] = {}
    if extra_cb is not None:
        try:
            engine_extra = extra_cb() or {}
        except Exception as e:
            log.warning("checkpoint extra-state capture failed: %s", e)
    counters: Dict[str, float] = {}
    if tel.enabled:
        counters = tel.snapshot()["counters"]
    payload = {
        "schema": 1,
        "iteration": int(gbdt.iter),
        "num_init_iteration": int(gbdt.num_init_iteration),
        "boosting": gbdt.name,
        "rank": int(tel.rank),
        "world": int(jax.process_count()),
        "k": int(k),
        "n_trees": len(gbdt.models),
        "n_valid": len(gbdt.valid_scores),
        # rank=-1: never salt the manifest hash with the health fault
        # injection — the manifest must describe the REAL model state
        "model_hash": model_state_hash(gbdt.models, rank=-1),
        "shrinkage_rate": float(gbdt.shrinkage_rate),
        "trees_meta": meta,
        "bag": bag,
        "feat_rng_x": int(gbdt.feat_rng.x),
        "best": [[ds, name, float(gbdt.best_score[(ds, name)]),
                  int(gbdt.best_iter.get((ds, name), 0))]
                 for (ds, name) in sorted(gbdt.best_score)],
        "boosting_extra": extra_payload,
        "engine_extra": engine_extra,
        "telemetry_counters": counters,
        "sanity": {key: getattr(cfg, key, None) for key in _SANITY_KEYS},
        # drift & lineage plane: the training DataProfile and provenance
        # record ride every checkpoint manifest, so a booster resurrected
        # from a checkpoint (rollover source) carries its training
        # distribution and lineage exactly like a model-file booster
        "data_profile": getattr(gbdt, "data_profile", None),
        "provenance": getattr(gbdt, "provenance", None),
    }
    return payload, arrays


# ------------------------------------------------------------- restore
def restore(gbdt, payload: Dict[str, Any], arrays) -> int:
    """Rebuild the driver's training state from a checkpoint snapshot;
    returns the restored iteration. Precondition: the booster was just
    constructed against the SAME dataset/params and every valid set was
    added (engine.train enforces the order)."""
    import jax
    import jax.numpy as jnp
    if payload.get("schema") != 1:
        log.fatal("unsupported checkpoint schema %r",
                  payload.get("schema"))
    if payload.get("boosting") != gbdt.name:
        log.fatal("checkpoint was written by boosting=%s; this run is %s",
                  payload.get("boosting"), gbdt.name)
    if int(payload.get("k", 0)) != gbdt.num_tree_per_iteration:
        log.fatal("checkpoint has %s trees/iteration, run has %d",
                  payload.get("k"), gbdt.num_tree_per_iteration)
    if int(payload.get("world", 1)) != jax.process_count():
        log.fatal("checkpoint was written by a %s-process run; this run "
                  "spans %d processes (score shards are rank-local)",
                  payload.get("world"), jax.process_count())
    if int(payload.get("n_valid", 0)) != len(gbdt.valid_scores):
        log.fatal("checkpoint carries %s valid sets, run has %d — add "
                  "the same valid sets before resuming",
                  payload.get("n_valid"), len(gbdt.valid_scores))
    sanity = payload.get("sanity") or {}
    cfg = gbdt.config
    drift = {key: (sanity.get(key), getattr(cfg, key, None))
             for key in _SANITY_KEYS
             if key in sanity and sanity[key] != getattr(cfg, key, None)}
    if drift:
        log.warning("resume with changed parameters (bit-identity to an "
                    "uninterrupted run is off): %s",
                    {key: f"{a!r}->{b!r}" for key, (a, b) in drift.items()})

    gbdt.drain_pending()
    models = trees_from_arrays(payload["trees_meta"], arrays)
    want = payload.get("model_hash", "")
    got = model_state_hash(models, rank=-1)
    if want and got != want:
        log.fatal("restored model hash %s does not match the manifest's "
                  "%s — torn or mismatched checkpoint", got[:16],
                  want[:16])
    gbdt.models[:] = models
    gbdt.device_trees[:] = [gbdt._device_tree_for_resume(ht)
                            for ht in models]
    gbdt.iter = int(payload["iteration"])
    gbdt.num_init_iteration = int(payload.get("num_init_iteration", 0))
    gbdt.shrinkage_rate = float(payload.get("shrinkage_rate",
                                            gbdt.shrinkage_rate))

    mp = getattr(gbdt, "mp", None)
    scores = np.asarray(arrays["scores"], np.float32)
    gbdt.scores = (mp.shard_local_cols(scores) if mp is not None
                   else jnp.asarray(scores))
    for vi in range(len(gbdt.valid_scores)):
        gbdt.valid_scores[vi] = jnp.asarray(
            np.asarray(arrays[f"vscore{vi}"], np.float32))

    gbdt.bag_streams.state = np.asarray(arrays["bag_stream_state"],
                                        np.uint32)
    bag = payload.get("bag") or {}
    gbdt.bag_cnt = int(bag.get("bag_cnt", gbdt.bag_cnt))
    if bag.get("is_bagging") and "bag_weight" in arrays:
        w = np.asarray(arrays["bag_weight"], np.float32)
        if mp is not None:
            gbdt._bag_weight_host = w
            gbdt.bag_weight = mp.shard_full(w)
        else:
            gbdt.bag_weight = jnp.asarray(w)
    cache: Dict[int, np.ndarray] = {}
    for j, key in enumerate(bag.get("cache_keys", [])):
        cache[int(key)] = np.asarray(arrays[f"bag_cache{j}"], bool)
    gbdt._bag_round_cache = cache or None
    gbdt.feat_rng.x = int(payload.get("feat_rng_x", gbdt.feat_rng.x))
    if "gain_ema" in arrays and getattr(gbdt, "use_screening", False):
        gbdt._gain_ema_dev = jnp.asarray(
            np.asarray(arrays["gain_ema"], np.float32))
        gbdt._screen_mask_cache = None
        gbdt._iter_gain_acc = None

    gbdt.best_score.clear()
    gbdt.best_iter.clear()
    for ds, name, score, it in payload.get("best", []):
        gbdt.best_score[(ds, name)] = float(score)
        gbdt.best_iter[(ds, name)] = int(it)

    gbdt._restore_boosting_extra(payload.get("boosting_extra") or {},
                                 arrays)
    gbdt.telemetry.restore_counters(payload.get("telemetry_counters")
                                    or {})
    # transient driver state: a fresh run continues from here
    gbdt._stopped_early = False
    gbdt._es_finished = False
    gbdt._es_carry = None
    gbdt._epi_carry = None
    gbdt._last_ckpt_iter = gbdt.iter
    # lineage: the resumed run descends from this checkpoint — chain the
    # parent hash into the (freshly built) provenance record
    gbdt._parent_ckpt_hash = str(want or got)
    prov = getattr(gbdt, "provenance", None)
    if prov is not None:
        prov["parent_checkpoint"] = gbdt._parent_ckpt_hash
    gbdt.telemetry.event("resumed", iteration=gbdt.iter,
                         trees=len(models),
                         model_hash=got[:16])
    log.info("resumed training at iteration %d (%d trees, hash %s)",
             gbdt.iter, len(models), got[:16])
    return gbdt.iter


def synthesize_es_carry(gbdt, es_state: Dict[str, Any]) -> bool:
    """Rebuild the megastep scan's device early-stop carry from a
    restored early_stopping-callback state. The carry is fully derivable
    from the callback's host state (same f32 values, same strict
    compares — metric/traced.py mirrors the callback's state machine),
    so checkpoints stay driver-agnostic: a sync-driver checkpoint
    resumes onto the megastep and vice versa."""
    import jax.numpy as jnp
    plan = gbdt._traced_plan
    if plan is None or not es_state.get("inited"):
        return False
    slots = plan.slots
    best_scores = es_state.get("best_score") or []
    best_iters = es_state.get("best_iter") or []
    seen = es_state.get("seen") or []
    if len(best_scores) != len(slots):
        log.warning("restored early-stop state covers %d slots, the "
                    "traced plan has %d; device carry starts fresh",
                    len(best_scores), len(slots))
        return False
    sign = np.asarray([1.0 if bigger else -1.0
                       for (_, _, bigger) in slots], np.float32)
    best = np.full(len(slots), -np.inf, np.float32)
    bround = np.full(len(slots), -1, np.int32)
    for i in range(len(slots)):
        if i < len(seen) and seen[i]:
            best[i] = np.float32(best_scores[i]) * sign[i]
            bround[i] = np.int32(best_iters[i])
    gbdt._es_carry = (jnp.asarray(best), jnp.asarray(bround),
                      jnp.zeros((), bool),
                      jnp.full((), -1, jnp.int32))
    return True


# -------------------------------------------------- booster-level entry
def resolve_checkpoint(path: str, world: int) -> str:
    """Accept either a concrete ``ckpt_*`` directory or a checkpoint
    root (selects the newest complete hash-consistent one)."""
    import os

    from .checkpoint import checkpoint_manifests, select_checkpoint
    if not os.path.isdir(path):
        log.fatal("resume path %r is not a directory", path)
    if checkpoint_manifests(path, world) is not None:
        return path
    sel = select_checkpoint(path, world)
    if sel is None:
        log.fatal("no complete %d-rank checkpoint under %r "
                  "(torn or missing manifests)", world, path)
    return sel


def restore_into_booster(booster, path: str) -> Dict[str, Any]:
    """Load this rank's slice of a checkpoint and restore the booster's
    driver; returns the payload (the engine applies callback state and
    the ES carry from payload['engine_extra'])."""
    import jax

    from .checkpoint import load_rank
    gbdt = booster._gbdt
    if gbdt is None:
        log.fatal("resume requires a booster constructed with a train_set")
    world = jax.process_count()
    cdir = resolve_checkpoint(str(path), world)
    payload, arrays = load_rank(cdir, gbdt.telemetry.rank)
    restore(gbdt, payload, arrays)
    booster.best_iteration = -1
    booster._model_version += 1
    return payload


def booster_from_checkpoint(path: str, rank: int = 0):
    """Standalone (prediction/serving-only) ``Booster`` from a
    resilience checkpoint — the train→serve rollover source.

    Accepts a concrete ``ckpt_<n>`` directory or a checkpoint root
    (newest checkpoint with a valid ``rank{rank}`` manifest; the model
    is replicated across ranks, so rank 0's trees ARE the full model).
    Trees restore f64-binary-exact (:func:`trees_from_arrays`) and are
    hash-verified against the manifest; objective / num_class /
    averaging come from the checkpoint's sanity block so
    finalize-prediction semantics match the training run.  No training
    dataset is attached — serving packs it through the raw device
    predictor, exactly like a model-file booster.
    """
    import os

    from ..basic import Booster
    from ..objective import create_objective_from_string
    from .checkpoint import _read_manifest, list_checkpoints, load_rank

    cdir = str(path)

    def _has_rank(d: str) -> bool:
        return _read_manifest(
            os.path.join(d, f"rank{rank}.json")) is not None

    if not (os.path.isdir(cdir) and _has_rank(cdir)):
        sel = next((p for _, p in list_checkpoints(cdir)
                    if _has_rank(p)), None) if os.path.isdir(cdir) \
            else None
        if sel is None:
            raise FileNotFoundError(
                f"no checkpoint with a valid rank{rank} manifest under "
                f"{path!r}")
        cdir = sel
    payload, arrays = load_rank(cdir, rank)
    models = trees_from_arrays(payload["trees_meta"], arrays)
    want = payload.get("model_hash", "")
    got = model_state_hash(models, rank=-1)
    if want and got != want:
        raise ValueError(
            f"checkpoint {cdir!r}: restored model hash {got[:16]} does "
            f"not match the manifest's {want[:16]} — torn or mismatched "
            "checkpoint")
    sanity = payload.get("sanity") or {}
    b = Booster()
    b.models = models
    b.num_tree_per_iteration = max(1, int(payload.get("k", 1)))
    b.num_class = max(1, int(sanity.get("num_class") or 1))
    # rf averages its trees; every other boosting mode sums
    b.average_output = payload.get("boosting") == "rf"
    max_feat = 0
    for ht in models:
        sf = np.asarray(ht.split_feature)
        if sf.size:
            max_feat = max(max_feat, int(sf.max()))
    b.max_feature_idx = max_feat
    obj = str(sanity.get("objective") or "none")
    if b.num_class > 1 and "num_class" not in obj:
        obj = f"{obj} num_class:{b.num_class}"
    b._objective_str = obj
    b.objective = create_objective_from_string(obj)
    b.data_profile = payload.get("data_profile")
    b.provenance = payload.get("provenance")
    b.best_iteration = -1
    b._model_version += 1
    log.info("rollover source: checkpoint %s (iteration %s, %d trees, "
             "hash %s)", cdir, payload.get("iteration"), len(models),
             got[:16])
    return b


def callback_states(callbacks: List) -> List[Dict[str, Any]]:
    """Serializable state of every stateful callback (those exposing
    ``_cb_state``), tagged by kind + position."""
    out = []
    for pos, cb in enumerate(callbacks):
        state_fn = getattr(cb, "_cb_state", None)
        if state_fn is None:
            continue
        try:
            st = state_fn()
        except Exception as e:
            log.warning("callback state capture failed: %s", e)
            continue
        out.append({"kind": getattr(cb, "_megastep_replay",
                                    type(cb).__name__),
                    "pos": pos, "state": st})
    return out


def restore_callback_states(callbacks: List, saved: List[Dict[str, Any]],
                            env) -> Optional[Dict[str, Any]]:
    """Feed saved states back into matching callbacks (by kind, in
    order); returns the restored early_stopping state (for the ES-carry
    synthesis) when one was present."""
    es_state = None
    by_kind: Dict[str, List[Dict]] = {}
    for ent in saved or []:
        by_kind.setdefault(ent.get("kind", ""), []).append(ent)
    for cb in callbacks:
        kind = getattr(cb, "_megastep_replay", None)
        restore_fn = getattr(cb, "_cb_restore", None)
        if restore_fn is None or kind is None:
            continue
        pool = by_kind.get(kind)
        if not pool:
            continue
        ent = pool.pop(0)
        try:
            restore_fn(ent["state"], env)
        except Exception as e:
            if kind == "early_stopping":
                # a broken ES restore (e.g. the slot count changed
                # across the resume) silently changes the stopping
                # decision — the one thing the resume API promises not
                # to do. Fail loudly instead of training on.
                log.fatal("early-stopping state restore failed: %s — "
                          "resume with the same valid sets/metrics the "
                          "interrupted run used, or drop the "
                          "early_stopping callback", e)
            log.warning("callback state restore failed (%s): %s", kind, e)
            continue
        if kind == "early_stopping":
            es_state = ent["state"]
    return es_state


def eval_list_from_payload(payload: Dict[str, Any]) -> List[tuple]:
    ev = (payload.get("engine_extra") or {}).get("eval_list") or []
    return [tuple(t) for t in ev]


def dumps_compact(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), default=str)
