"""Elastic fault tolerance for long multi-chip runs (ROADMAP item 5).

The XLA-collective training path makes a single rank crash fatal to the
whole cohort — unlike the reference's socket layer, there is no
per-message retry to hide behind. This package turns that hard failure
mode into bounded lost work:

- :mod:`atomicio` — write-then-rename file helpers; a crash mid-write
  can never leave a truncated model/checkpoint on disk;
- :mod:`checkpoint` — double-buffered async checkpoints: the driver
  captures the complete resumable state at megastep drain boundaries
  (and every ``checkpoint_period`` iterations on the sync driver), a
  background thread serializes + commits it with a per-rank manifest
  (rank, iteration, model-state hash);
- :mod:`state` — capture/restore of the GBDT driver's training state
  (models, score carries, bagging/feature RNG stream positions,
  early-stop state, telemetry counters) with bit-identical resume;
- :mod:`recovery` — auto-recovery from health-auditor findings: a
  diverged rank re-syncs model state from rank 0's hash-verified
  serialization through the host collective layer;
- :mod:`faults` — deterministic fault injection registry
  (crash/hang/diverge/torn-checkpoint at a fixed iteration+rank) for
  the chaos tests;
- :mod:`comms` — timeout + bounded-retry guards around the host-plane
  collectives so a hung peer degrades to a structured failure instead
  of a deadlock.

Launcher-level supervised respawn lives in
:func:`lightgbm_tpu.parallel.launcher.train_distributed`; resume enters
through ``engine.train(resume_from=...)`` / CLI ``task=train
resume=<path>``. See docs/Reliability.md.
"""
from __future__ import annotations

from .atomicio import atomic_write_bytes, atomic_write_json, atomic_write_text
from .checkpoint import (CheckpointManager, list_checkpoints, load_rank,
                         select_checkpoint)
from .comms import CollectiveError, guarded_call, set_collective_policy
from .faults import FaultRegistry, registry_from_env

__all__ = [
    "atomic_write_bytes", "atomic_write_json", "atomic_write_text",
    "CheckpointManager", "list_checkpoints", "load_rank",
    "select_checkpoint",
    "CollectiveError", "guarded_call", "set_collective_policy",
    "FaultRegistry", "registry_from_env",
]
