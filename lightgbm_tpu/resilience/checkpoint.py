"""Async double-buffered training checkpoints.

Layout (one directory per checkpoint, per-rank files inside)::

    <checkpoint_dir>/
      ckpt_0000000008/
        rank0.npz     — arrays (trees, score carries, RNG streams)
        rank0.json    — manifest: schema, rank, world, iteration,
                        model-state hash, npz byte size, JSON payload
      ckpt_0000000016/
        ...

Commit protocol per rank: the ``.npz`` is written tmp→fsync→rename,
then the manifest tmp→fsync→rename — the manifest's existence commits
the rank's participation, so a crash at ANY point mid-write leaves the
previous checkpoint untouched and the new one simply incomplete
(:func:`select_checkpoint` skips it). Retention keeps the newest
``keep`` complete checkpoints per rank (double buffering: the previous
checkpoint is pruned only after the next one commits).

Writing happens on a background thread: the training loop hands over an
already-captured host snapshot (numpy arrays + JSON payload) and keeps
going; serialization + fsync + rename + pruning never block an
iteration. ``wait()`` joins the in-flight write (tests, end of
training, checkpoint-now recovery actions).
"""
from __future__ import annotations

import io
import json
import os
import queue
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import log
from .atomicio import atomic_write_bytes, atomic_write_json

SCHEMA_VERSION = 1
_CKPT_RE = re.compile(r"^ckpt_(\d{10})$")


def _ckpt_dirname(iteration: int) -> str:
    return f"ckpt_{int(iteration):010d}"


def list_checkpoints(root: str) -> List[Tuple[int, str]]:
    """(iteration, path) of every checkpoint directory under ``root``,
    newest first. Existence only — completeness is the selector's job."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort(reverse=True)
    return out


def _read_manifest(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            man = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(man, dict) or man.get("schema") != SCHEMA_VERSION:
        return None
    return man


def checkpoint_manifests(path: str, world: int) -> Optional[List[Dict]]:
    """All ``world`` rank manifests of one checkpoint directory when the
    checkpoint is complete AND consistent (every rank present, same
    iteration, same model-state hash, npz readable at the recorded
    size); None otherwise — a torn write fails one of these checks."""
    mans = []
    for r in range(world):
        man = _read_manifest(os.path.join(path, f"rank{r}.json"))
        if man is None or int(man.get("rank", -1)) != r \
                or int(man.get("world", 0)) != world:
            return None
        npz = os.path.join(path, man.get("npz", ""))
        try:
            if os.path.getsize(npz) != int(man.get("npz_bytes", -1)):
                return None
        except OSError:
            return None
        mans.append(man)
    iters = {int(m["iteration"]) for m in mans}
    hashes = {m.get("model_hash") for m in mans}
    if len(iters) != 1 or len(hashes) != 1:
        return None
    return mans


def select_checkpoint(root: str, world: int) -> Optional[str]:
    """Newest checkpoint directory complete + hash-consistent across all
    ``world`` ranks — the launcher's restart point."""
    for _, path in list_checkpoints(root):
        if checkpoint_manifests(path, world) is not None:
            return path
    return None


def load_rank(path: str, rank: int):
    """(payload dict, npz mapping) for one rank of a checkpoint dir.
    Raises with a pointed message on a missing/torn checkpoint — resume
    must fail loudly, not train silently from nothing."""
    man = _read_manifest(os.path.join(path, f"rank{rank}.json"))
    if man is None:
        raise FileNotFoundError(
            f"no valid rank{rank} manifest in checkpoint {path!r} "
            "(incomplete or torn write — pick a checkpoint "
            "select_checkpoint accepts)")
    npz_path = os.path.join(path, man["npz"])
    arrays = np.load(npz_path, allow_pickle=False)
    return man["payload"], arrays


def encode_npz(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


class CheckpointManager:
    """Per-rank async checkpoint writer over one checkpoint root."""

    def __init__(self, root: str, rank: int, world: int, keep: int = 2,
                 telemetry=None, async_io: bool = True):
        self.root = str(root)
        self.rank = int(rank)
        self.world = max(1, int(world))
        self.keep = max(1, int(keep))
        self.telemetry = telemetry
        self.last_error: Optional[str] = None
        # (iteration, path, model_hash) of the newest committed write —
        # the crash flight recorder records this as the resume hint
        self.last_written: Optional[Dict[str, Any]] = None
        os.makedirs(self.root, exist_ok=True)
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._async = bool(async_io)
        self._worker: Optional[threading.Thread] = None
        if self._async:
            self._worker = threading.Thread(
                target=self._run, daemon=True,
                name=f"ckpt-writer-rank{self.rank}")
            self._worker.start()

    # ------------------------------------------------------------ write
    def save(self, iteration: int, payload: Dict[str, Any],
             arrays: Dict[str, np.ndarray]) -> None:
        """Enqueue one checkpoint. The snapshot is already host-resident
        and owned by the writer from here on. Blocks only when a prior
        write is still in flight (bounded queue of one: checkpoints are
        ordered, and back-to-back saves faster than the disk is a
        configuration problem surfaced as backpressure, not unbounded
        memory)."""
        job = (int(iteration), payload, arrays)
        if not self._async:
            self._write(*job)
            return
        self._q.put(job)

    def wait(self, timeout: float = 120.0) -> None:
        """Block until every enqueued write has committed.
        ``unfinished_tasks`` (incremented at put(), decremented only
        after the write completes via task_done) covers the window
        between the worker's get() and the write — an emptiness check
        would not."""
        if self._async:
            deadline = time.time() + timeout
            while self._q.unfinished_tasks:
                if time.time() > deadline:
                    raise TimeoutError("checkpoint writer did not drain")
                time.sleep(0.01)

    def close(self, timeout: float = 120.0) -> None:
        """Drain the queue and stop the worker thread (manager is dead
        afterwards — reset_config replaces, never reuses)."""
        if not self._async or self._worker is None:
            return
        self.wait(timeout)
        self._q.put(None)           # worker exit sentinel
        self._worker.join(timeout=5.0)
        self._worker = None

    def _run(self) -> None:
        while True:
            job = self._q.get()
            try:
                if job is None:
                    return
                self._write(*job)
            finally:
                self._q.task_done()

    def _write(self, iteration: int, payload: Dict[str, Any],
               arrays: Dict[str, np.ndarray]) -> None:
        t0 = time.perf_counter()
        tel = self.telemetry
        try:
            cdir = os.path.join(self.root, _ckpt_dirname(iteration))
            os.makedirs(cdir, exist_ok=True)
            blob = encode_npz(arrays)
            npz_name = f"rank{self.rank}.npz"
            from . import faults
            if faults.torn_checkpoint_due(iteration, self.rank):
                # chaos hook: simulate a crash mid-write — half the npz
                # bytes, no manifest. Deliberately NOT routed through
                # atomic_write (the torn artifact must be visible), and
                # the selector must skip this checkpoint.
                log.warning("fault injection: torn checkpoint write at "
                            "iteration %d", iteration)
                with open(os.path.join(cdir, npz_name), "wb") as fh:
                    fh.write(blob[:max(1, len(blob) // 2)])
                if tel is not None:
                    tel.event("fault_injected", kind="torn_ckpt",
                              iteration=iteration)
                return
            atomic_write_bytes(os.path.join(cdir, npz_name), blob)
            manifest = {
                "schema": SCHEMA_VERSION,
                "rank": self.rank,
                "world": self.world,
                "iteration": int(iteration),
                "model_hash": payload.get("model_hash", ""),
                "npz": npz_name,
                "npz_bytes": len(blob),
                "ts": time.time(),
                "payload": payload,
            }
            # the manifest commits this rank's participation — LAST
            atomic_write_json(os.path.join(cdir, f"rank{self.rank}.json"),
                              manifest)
            self.last_written = {"iteration": int(iteration),
                                 "path": cdir,
                                 "model_hash": payload.get("model_hash",
                                                           "")}
            self.last_error = None
            dt = time.perf_counter() - t0
            if tel is not None and tel.enabled:
                tel.inc("ckpt.written")
                # checkpoint-age feed for the SLO plane: the
                # train.checkpoint_age objective measures now - this
                tel.gauge("ckpt.last_write_ts", time.time())
                tel.event("checkpoint_written", iteration=iteration,
                          path=cdir, bytes=len(blob),
                          seconds=round(dt, 4))
            self._prune()
        except Exception as e:
            # a checkpoint failure must never kill training — the run is
            # healthy, only its insurance lapsed; say so loudly
            self.last_error = f"{type(e).__name__}: {e}"
            log.warning("checkpoint write at iteration %d failed: %s",
                        iteration, self.last_error)
            if tel is not None and tel.enabled:
                tel.inc("ckpt.failed")
                tel.event("checkpoint_failed", iteration=iteration,
                          error=self.last_error[:500])

    # ------------------------------------------------------------ prune
    def _prune(self) -> None:
        """Remove THIS rank's files from checkpoints older than the
        newest ``keep`` ones that carry this rank's manifest, then
        rmdir best-effort (succeeds once the last rank pruned). Pruning
        only ever runs after a newer checkpoint committed, so the
        double-buffer invariant holds: at any instant at least one
        complete checkpoint survives any crash."""
        mine = [(it, path) for it, path in list_checkpoints(self.root)
                if os.path.exists(os.path.join(path,
                                               f"rank{self.rank}.json"))]
        for it, path in mine[self.keep:]:
            for name in (f"rank{self.rank}.json", f"rank{self.rank}.npz"):
                try:
                    os.remove(os.path.join(path, name))
                except OSError:
                    pass
            try:
                os.rmdir(path)
            except OSError:
                pass  # other ranks' files still inside
