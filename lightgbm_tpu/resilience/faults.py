"""Deterministic fault injection for chaos testing.

Generalizes the ``LIGHTGBM_TPU_HEALTH_FAULT_RANK`` hash-salt pattern
(obs/health.py) into a small registry of injectable faults driven by the
``LIGHTGBM_TPU_FAULTS`` environment variable — a comma-separated list of
specs::

    kind@iteration[:rank=R]

    crash@5:rank=1      process os._exit(43) when training reaches
                        iteration 5 on rank 1 (the launcher-respawn
                        chaos test's trigger)
    hang@6:rank=0       sleep "forever" at iteration 6 on rank 0 so the
                        peers' guarded collectives time out
    diverge@4:rank=1    corrupt the latest materialized tree on rank 1
                        (model AND that rank's score rows, keeping the
                        rank-internal invariant) so the health auditor
                        detects a real divergence the resync can repair
    torn_ckpt@3         truncate rank's checkpoint write at iteration 3
                        and skip the manifest — simulates a crash
                        mid-write; the selector must skip it

Serve-plane faults (the unit is the micro-batcher's BATCH sequence
number, not a training iteration; hooks fire from inside the worker's
dispatch try-block, so an injected error resolves the batch's futures
exactly like a real dispatch failure — serving chaos CI's triggers):

    serve_slow_dispatch@2:ms=300   sleep 300 ms before dispatching
                        batch 2 (default 250) — an overloaded/throttled
                        device; deadline shedding must absorb the spike
    serve_dispatch_error@3         raise ServeFaultError in batch 3's
                        dispatch: the batch's futures must resolve with
                        the error and the NEXT batch must serve fine
    serve_wedge_worker@2           sleep "forever" inside batch 2's
                        dispatch: close() must detect the wedged
                        worker, fail queued+in-flight futures with
                        ServeWorkerWedged and emit serve_worker_wedged

Every fault fires at most once per *run lineage*: when
``LIGHTGBM_TPU_FAULT_STATE`` names a directory, a marker file records
the firing so a respawned process (same env, fresh pid) does not
re-crash forever — the launcher points this at its scratch directory.
Without a state dir, firing state is process-local.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ..utils import log

FAULTS_ENV = "LIGHTGBM_TPU_FAULTS"
FAULT_STATE_ENV = "LIGHTGBM_TPU_FAULT_STATE"

CRASH_EXIT_CODE = 43


class ServeFaultError(RuntimeError):
    """Injected serving dispatch failure (``serve_dispatch_error``):
    raised inside the micro-batcher's dispatch try-block so the batch's
    futures resolve with it exactly like a real device failure."""


class Fault:
    __slots__ = ("kind", "iteration", "rank", "mods")

    def __init__(self, kind: str, iteration: int, rank: int = -1,
                 mods: Optional[Dict[str, str]] = None):
        self.kind = kind
        self.iteration = int(iteration)
        self.rank = int(rank)
        # generic key=value modifiers past rank= (serve_slow_dispatch's
        # ms=, future knobs) — parsed once, read by the hooks
        self.mods: Dict[str, str] = dict(mods or {})

    def key(self) -> str:
        return f"{self.kind}@{self.iteration}.rank{self.rank}"


def parse_faults(spec: str) -> List[Fault]:
    faults: List[Fault] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            head, *mods = part.split(":")
            kind, it = head.split("@", 1)
            rank = -1
            extra: Dict[str, str] = {}
            for m in mods:
                if m.startswith("rank="):
                    rank = int(m[5:])
                elif "=" in m:
                    mk, mv = m.split("=", 1)
                    extra[mk.strip()] = mv.strip()
            faults.append(Fault(kind.strip(), int(it), rank, extra))
        except (ValueError, IndexError):
            log.warning("ignoring malformed fault spec %r "
                        "(expected kind@iteration[:rank=R][:k=v])", part)
    return faults


class FaultRegistry:
    """Parsed faults + at-most-once firing bookkeeping."""

    def __init__(self, faults: List[Fault], state_dir: str = ""):
        self.faults = faults
        self.state_dir = state_dir
        self._fired: set = set()

    def _already_fired(self, f: Fault) -> bool:
        if f.key() in self._fired:
            return True
        if self.state_dir:
            return os.path.exists(os.path.join(self.state_dir, f.key()))
        return False

    def _mark_fired(self, f: Fault) -> None:
        self._fired.add(f.key())
        if self.state_dir:
            try:
                os.makedirs(self.state_dir, exist_ok=True)
                # marker content is informational; existence is the bit.
                # Written non-atomically on purpose: a crash fault exits
                # the process right after, and a half-written marker
                # still exists (which is all the respawn check needs)
                with open(os.path.join(self.state_dir, f.key()), "w") as fh:
                    fh.write(str(time.time()))
            except OSError as e:
                log.warning("fault marker write failed: %s", e)

    def due(self, kind: str, iteration: int, rank: int,
            at_or_after: bool = False) -> Optional[Fault]:
        """The first un-fired fault of ``kind`` matching this rank whose
        iteration equals ``iteration`` (or is <= it, for drivers that
        advance several iterations per step); marks it fired."""
        for f in self.faults:
            if f.kind != kind:
                continue
            if f.rank >= 0 and f.rank != int(rank):
                continue
            hit = (f.iteration <= iteration) if at_or_after \
                else (f.iteration == iteration)
            if hit and not self._already_fired(f):
                self._mark_fired(f)
                return f
        return None

    def __bool__(self) -> bool:
        return bool(self.faults)


_EMPTY = FaultRegistry([])
_CACHE: Dict[str, FaultRegistry] = {}


def registry_from_env() -> FaultRegistry:
    """Registry for the current env var values (cached per value so the
    parse + warning happen once; firing state is shared per spec)."""
    spec = os.environ.get(FAULTS_ENV, "")
    if not spec:
        return _EMPTY
    state = os.environ.get(FAULT_STATE_ENV, "")
    key = f"{spec}|{state}"
    reg = _CACHE.get(key)
    if reg is None:
        reg = _CACHE[key] = FaultRegistry(parse_faults(spec), state)
    return reg


# ---------------------------------------------------------------- hooks
def on_training_step(gbdt) -> None:
    """Called by the driver at the top of every training step: fires
    crash and hang faults once the training iteration reaches the
    fault's iteration (``at_or_after`` so multi-iteration megastep
    chunks cannot jump over the trigger)."""
    reg = getattr(gbdt, "_faults", None) or _EMPTY
    if not reg:
        return
    rank = gbdt.telemetry.rank
    it = int(gbdt.iter)
    f = reg.due("crash", it, rank, at_or_after=True)
    if f is not None:
        log.warning("fault injection: crashing rank %d at iteration %d",
                    rank, it)
        gbdt.telemetry.event("fault_injected", kind="crash", iteration=it)
        try:
            gbdt.telemetry.flush()
        except Exception:
            pass
        os._exit(CRASH_EXIT_CODE)
    f = reg.due("hang", it, rank, at_or_after=True)
    if f is not None:
        log.warning("fault injection: hanging rank %d at iteration %d",
                    rank, it)
        gbdt.telemetry.event("fault_injected", kind="hang", iteration=it)
        time.sleep(10 ** 7)


def maybe_diverge(gbdt, iteration: int) -> None:
    """Fires the ``diverge`` fault: corrupts the newest materialized
    tree on the target rank (see recovery.inject_divergence) so the
    next health check sees a genuine cross-rank model mismatch."""
    reg = getattr(gbdt, "_faults", None) or _EMPTY
    if not reg:
        return
    f = reg.due("diverge", int(iteration), gbdt.telemetry.rank)
    if f is not None:
        from . import recovery
        recovery.inject_divergence(gbdt, int(iteration))


def _serve_event(telemetry, kind: str, batch: int, **attrs) -> None:
    """Best-effort fault_injected event: the injection itself must not
    depend on a healthy telemetry sink."""
    if telemetry is None:
        return
    try:
        telemetry.event("fault_injected", kind=kind, batch=batch, **attrs)
    except Exception:
        pass


def on_serve_batch(telemetry, batch_index: int) -> None:
    """Serve-plane fault hook, called by the micro-batcher INSIDE its
    dispatch try-block once per micro-batch (``batch_index`` is the
    1-based batch sequence number; ``at_or_after`` so a spec's index
    cannot be jumped over by coalescing).  May sleep
    (``serve_slow_dispatch``, ``ms=`` modifier, default 250), sleep
    forever (``serve_wedge_worker`` — close() must detect the wedge),
    or raise :class:`ServeFaultError` (``serve_dispatch_error`` — the
    batch's futures resolve with it; the worker must survive)."""
    reg = registry_from_env()
    if not reg:
        return
    rank = int(getattr(telemetry, "rank", 0) or 0) \
        if telemetry is not None else 0
    f = reg.due("serve_slow_dispatch", batch_index, rank,
                at_or_after=True)
    if f is not None:
        ms = float(f.mods.get("ms", 250.0))
        log.warning("fault injection: slow serve dispatch (%g ms) at "
                    "batch %d", ms, batch_index)
        _serve_event(telemetry, "serve_slow_dispatch", batch_index, ms=ms)
        time.sleep(ms / 1000.0)
    f = reg.due("serve_wedge_worker", batch_index, rank,
                at_or_after=True)
    if f is not None:
        log.warning("fault injection: wedging serve worker at batch %d",
                    batch_index)
        _serve_event(telemetry, "serve_wedge_worker", batch_index)
        time.sleep(10 ** 7)
    f = reg.due("serve_dispatch_error", batch_index, rank,
                at_or_after=True)
    if f is not None:
        log.warning("fault injection: serve dispatch error at batch %d",
                    batch_index)
        _serve_event(telemetry, "serve_dispatch_error", batch_index)
        raise ServeFaultError(
            f"injected serve_dispatch_error at batch {batch_index}")


def torn_checkpoint_due(iteration: int, rank: int) -> bool:
    reg = registry_from_env()
    if not reg:
        return False
    return reg.due("torn_ckpt", int(iteration), int(rank),
                   at_or_after=True) is not None
