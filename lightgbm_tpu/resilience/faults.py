"""Deterministic fault injection for chaos testing.

Generalizes the ``LIGHTGBM_TPU_HEALTH_FAULT_RANK`` hash-salt pattern
(obs/health.py) into a small registry of injectable faults driven by the
``LIGHTGBM_TPU_FAULTS`` environment variable — a comma-separated list of
specs::

    kind@iteration[:rank=R]

    crash@5:rank=1      process os._exit(43) when training reaches
                        iteration 5 on rank 1 (the launcher-respawn
                        chaos test's trigger)
    hang@6:rank=0       sleep "forever" at iteration 6 on rank 0 so the
                        peers' guarded collectives time out
    diverge@4:rank=1    corrupt the latest materialized tree on rank 1
                        (model AND that rank's score rows, keeping the
                        rank-internal invariant) so the health auditor
                        detects a real divergence the resync can repair
    torn_ckpt@3         truncate rank's checkpoint write at iteration 3
                        and skip the manifest — simulates a crash
                        mid-write; the selector must skip it

Every fault fires at most once per *run lineage*: when
``LIGHTGBM_TPU_FAULT_STATE`` names a directory, a marker file records
the firing so a respawned process (same env, fresh pid) does not
re-crash forever — the launcher points this at its scratch directory.
Without a state dir, firing state is process-local.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ..utils import log

FAULTS_ENV = "LIGHTGBM_TPU_FAULTS"
FAULT_STATE_ENV = "LIGHTGBM_TPU_FAULT_STATE"

CRASH_EXIT_CODE = 43


class Fault:
    __slots__ = ("kind", "iteration", "rank")

    def __init__(self, kind: str, iteration: int, rank: int = -1):
        self.kind = kind
        self.iteration = int(iteration)
        self.rank = int(rank)

    def key(self) -> str:
        return f"{self.kind}@{self.iteration}.rank{self.rank}"


def parse_faults(spec: str) -> List[Fault]:
    faults: List[Fault] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            head, *mods = part.split(":")
            kind, it = head.split("@", 1)
            rank = -1
            for m in mods:
                if m.startswith("rank="):
                    rank = int(m[5:])
            faults.append(Fault(kind.strip(), int(it), rank))
        except (ValueError, IndexError):
            log.warning("ignoring malformed fault spec %r "
                        "(expected kind@iteration[:rank=R])", part)
    return faults


class FaultRegistry:
    """Parsed faults + at-most-once firing bookkeeping."""

    def __init__(self, faults: List[Fault], state_dir: str = ""):
        self.faults = faults
        self.state_dir = state_dir
        self._fired: set = set()

    def _already_fired(self, f: Fault) -> bool:
        if f.key() in self._fired:
            return True
        if self.state_dir:
            return os.path.exists(os.path.join(self.state_dir, f.key()))
        return False

    def _mark_fired(self, f: Fault) -> None:
        self._fired.add(f.key())
        if self.state_dir:
            try:
                os.makedirs(self.state_dir, exist_ok=True)
                # marker content is informational; existence is the bit.
                # Written non-atomically on purpose: a crash fault exits
                # the process right after, and a half-written marker
                # still exists (which is all the respawn check needs)
                with open(os.path.join(self.state_dir, f.key()), "w") as fh:
                    fh.write(str(time.time()))
            except OSError as e:
                log.warning("fault marker write failed: %s", e)

    def due(self, kind: str, iteration: int, rank: int,
            at_or_after: bool = False) -> Optional[Fault]:
        """The first un-fired fault of ``kind`` matching this rank whose
        iteration equals ``iteration`` (or is <= it, for drivers that
        advance several iterations per step); marks it fired."""
        for f in self.faults:
            if f.kind != kind:
                continue
            if f.rank >= 0 and f.rank != int(rank):
                continue
            hit = (f.iteration <= iteration) if at_or_after \
                else (f.iteration == iteration)
            if hit and not self._already_fired(f):
                self._mark_fired(f)
                return f
        return None

    def __bool__(self) -> bool:
        return bool(self.faults)


_EMPTY = FaultRegistry([])
_CACHE: Dict[str, FaultRegistry] = {}


def registry_from_env() -> FaultRegistry:
    """Registry for the current env var values (cached per value so the
    parse + warning happen once; firing state is shared per spec)."""
    spec = os.environ.get(FAULTS_ENV, "")
    if not spec:
        return _EMPTY
    state = os.environ.get(FAULT_STATE_ENV, "")
    key = f"{spec}|{state}"
    reg = _CACHE.get(key)
    if reg is None:
        reg = _CACHE[key] = FaultRegistry(parse_faults(spec), state)
    return reg


# ---------------------------------------------------------------- hooks
def on_training_step(gbdt) -> None:
    """Called by the driver at the top of every training step: fires
    crash and hang faults once the training iteration reaches the
    fault's iteration (``at_or_after`` so multi-iteration megastep
    chunks cannot jump over the trigger)."""
    reg = getattr(gbdt, "_faults", None) or _EMPTY
    if not reg:
        return
    rank = gbdt.telemetry.rank
    it = int(gbdt.iter)
    f = reg.due("crash", it, rank, at_or_after=True)
    if f is not None:
        log.warning("fault injection: crashing rank %d at iteration %d",
                    rank, it)
        gbdt.telemetry.event("fault_injected", kind="crash", iteration=it)
        try:
            gbdt.telemetry.flush()
        except Exception:
            pass
        os._exit(CRASH_EXIT_CODE)
    f = reg.due("hang", it, rank, at_or_after=True)
    if f is not None:
        log.warning("fault injection: hanging rank %d at iteration %d",
                    rank, it)
        gbdt.telemetry.event("fault_injected", kind="hang", iteration=it)
        time.sleep(10 ** 7)


def maybe_diverge(gbdt, iteration: int) -> None:
    """Fires the ``diverge`` fault: corrupts the newest materialized
    tree on the target rank (see recovery.inject_divergence) so the
    next health check sees a genuine cross-rank model mismatch."""
    reg = getattr(gbdt, "_faults", None) or _EMPTY
    if not reg:
        return
    f = reg.due("diverge", int(iteration), gbdt.telemetry.rank)
    if f is not None:
        from . import recovery
        recovery.inject_divergence(gbdt, int(iteration))


def torn_checkpoint_due(iteration: int, rank: int) -> bool:
    reg = registry_from_env()
    if not reg:
        return False
    return reg.due("torn_ckpt", int(iteration), int(rank),
                   at_or_after=True) is not None
