"""Timeout + bounded-retry guards for host-plane collectives.

The multiproc layout's ``process_allgather`` calls and the telemetry
``allgather_json`` helper block in native code (gloo / the TPU runtime):
a peer that hangs mid-iteration leaves every other rank wedged inside
the collective forever — the launcher's only recourse would be its
whole-run timeout. With a collective timeout configured
(``collective_timeout`` config key, seconds; 0 = off, the default), the
blocking call runs on a watchdog thread and a hung peer degrades to a
structured :class:`CollectiveError` on the waiting ranks, which unwinds
through the crash flight recorder and lets the launcher respawn the
cohort from the newest consistent checkpoint.

Transient *errors* raised by the collective itself (transport hiccups)
are retried a bounded number of times; a timeout is never retried —
the peers' collective pairing is already lost at that point, and a
retry would pair with the wrong round.
"""
from __future__ import annotations

import threading
import time
from typing import Callable

from ..utils import log

# process-wide policy: set once from the driver's config
# (_setup_resilience); the launcher sets the config key in every worker
_TIMEOUT_S = 0.0
_RETRIES = 2


class CollectiveError(RuntimeError):
    """A host-plane collective timed out or kept failing; carries the
    collective's name and the configured timeout for the flight
    recorder."""


def set_collective_policy(timeout_s: float, retries: int = 2) -> None:
    global _TIMEOUT_S, _RETRIES
    _TIMEOUT_S = max(0.0, float(timeout_s or 0.0))
    _RETRIES = max(0, int(retries))


def get_timeout() -> float:
    return _TIMEOUT_S


def _run_with_timeout(fn: Callable, what: str, timeout_s: float):
    box = {}
    done = threading.Event()

    def worker():
        try:
            box["result"] = fn()
        except BaseException as e:     # noqa: BLE001 — re-raised below
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True,
                         name=f"collective-{what}")
    t.start()
    if not done.wait(timeout_s):
        # the worker thread is abandoned (the native call cannot be
        # interrupted); the caller is expected to crash out through the
        # flight recorder, so the leak is bounded by process lifetime
        raise CollectiveError(
            f"host collective '{what}' timed out after {timeout_s:.1f}s "
            "(hung or dead peer); resume from the newest checkpoint")
    if "error" in box:
        raise box["error"]
    return box.get("result")


def guarded_call(fn: Callable, what: str = "allgather", telemetry=None):
    """Run a blocking host collective under the configured policy.

    With no timeout configured this is a direct call — zero overhead,
    zero behavior change (the tier-1 default). Errors retry up to the
    configured count with a short backoff; timeouts raise immediately.
    """
    timeout_s = _TIMEOUT_S
    if timeout_s <= 0.0:
        return fn()
    last = None
    for attempt in range(_RETRIES + 1):
        try:
            return _run_with_timeout(fn, what, timeout_s)
        except CollectiveError:
            if telemetry is not None and getattr(telemetry, "enabled",
                                                 False):
                telemetry.inc("comms.timeout")
                telemetry.event("collective_timeout", what=what,
                                timeout_s=timeout_s)
            raise
        except Exception as e:          # transport error: bounded retry
            last = e
            if telemetry is not None and getattr(telemetry, "enabled",
                                                 False):
                telemetry.inc("comms.retry")
            if attempt < _RETRIES:
                log.warning("host collective '%s' failed (%s: %s); "
                            "retry %d/%d", what, type(e).__name__,
                            str(e)[:200], attempt + 1, _RETRIES)
                time.sleep(0.5 * (attempt + 1))
    raise CollectiveError(
        f"host collective '{what}' failed after {_RETRIES + 1} attempts: "
        f"{type(last).__name__}: {str(last)[:300]}") from last
