"""Atomic file writes: write a temp sibling, fsync, rename into place.

POSIX ``rename`` within one filesystem is atomic, so any reader (a
resuming launcher, a model loader, the checkpoint selector) sees either
the previous complete file or the new complete file — never a
truncation. Every durable artifact this package produces (checkpoint
arrays, manifests) and the engine's ``snapshot_freq`` model snapshots
route through these helpers.
"""
from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Iterator


def _tmp_path(path: str) -> str:
    d, base = os.path.split(path)
    return os.path.join(d, f".{base}.tmp.{os.getpid()}")


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Write ``data`` to ``path`` atomically (temp file + rename)."""
    tmp = _tmp_path(path)
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            if fsync:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        # a failed write must not litter temp files next to checkpoints:
        # the selector treats unknown files as noise, but disk fills are
        # a real long-run failure mode
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def atomic_write_text(path: str, text: str, fsync: bool = True) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), fsync=fsync)


@contextlib.contextmanager
def atomic_stream(path: str, fsync: bool = True) -> Iterator[Any]:
    """Streaming variant of ``atomic_write_bytes`` for artifacts too big
    to hold in memory (the ingest binary dataset cache writes its packed
    bin matrix chunk by chunk): yields a binary file object positioned
    at the temp sibling; on clean exit the temp is fsynced and renamed
    into place, on ANY exception it is removed and ``path`` is left
    untouched — a reader can never see a half-written artifact."""
    tmp = _tmp_path(path)
    try:
        with open(tmp, "wb") as fh:
            yield fh
            if fsync:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def atomic_write_json(path: str, obj: Any, fsync: bool = True) -> None:
    atomic_write_text(path, json.dumps(obj, default=str), fsync=fsync)
