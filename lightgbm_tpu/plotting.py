"""Plotting utilities.

Behavioral analog of ref: python-package/lightgbm/plotting.py
(plot_importance, plot_metric, plot_split_value_histogram, plot_tree /
create_tree_digraph). matplotlib/graphviz are optional; informative errors
otherwise.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel


def _check_not_tuple_of_2_elements(obj, obj_name):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _to_booster(model) -> Booster:
    if isinstance(model, LGBMModel):
        return model.booster_
    if isinstance(model, Booster):
        return model
    raise TypeError("model should be a Booster or LGBMModel instance")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """Horizontal bar plot of feature importances
    (ref: plotting.py plot_importance)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("You must install matplotlib to plot importance."
                          ) from e
    booster = _to_booster(booster)
    importance = booster.feature_importance(importance_type=importance_type)
    names = booster.feature_name()
    tuples = sorted(zip(names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Cannot plot trees with zero importance")
    labels, values = zip(*tuples)

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    fmt = f"%.{precision}f" if importance_type == "gain" else "%d"
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, fmt % x, va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    else:
        ax.set_xlim(0, max(values) * 1.1)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "auto",
                figsize=None, dpi=None, grid: bool = True):
    """Plot one metric recorded during training
    (ref: plotting.py plot_metric). Accepts the evals_result dict from
    ``record_evaluation`` or a fitted LGBMModel."""
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("You must install matplotlib to plot metric."
                          ) from e
    if isinstance(booster, LGBMModel):
        eval_results = dict(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = booster
    else:
        raise TypeError("booster must be a dict from record_evaluation or "
                        "an LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    names = dataset_names or list(eval_results.keys())
    msv = None
    for name in names:
        metrics = eval_results[name]
        if metric is None:
            metric = next(iter(metrics))
        if metric not in metrics:
            raise ValueError(f"Metric {metric} was not recorded for {name}")
        results = metrics[metric]
        ax.plot(np.arange(len(results)), results, label=name)
        msv = metric
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    ax.set_ylabel(msv if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature "
                                     "with @index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid: bool = True):
    """Histogram of split thresholds used for one feature
    (ref: plotting.py plot_split_value_histogram)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("You must install matplotlib.") from e
    booster = _to_booster(booster)
    names = booster.feature_name()
    if isinstance(feature, str):
        fidx = names.index(feature)
    else:
        fidx = int(feature)
    values = []
    for t in booster.models:
        for i in range(t.num_internal):
            if int(t.split_feature[i]) == fidx and \
                    not (int(t.decision_type[i]) & 1):
                values.append(float(t.threshold[i]))
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centers, hist,
           width=width_coef * (bin_edges[1] - bin_edges[0]))
    if title:
        title = title.replace("@index/name@",
                              "name" if isinstance(feature, str) else
                              "index")
        title = title.replace("@feature@", str(feature))
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: int = 3, orientation: str = "horizontal",
                        **kwargs):
    """Graphviz Digraph of one tree (ref: plotting.py create_tree_digraph).
    Requires the ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("You must install graphviz to plot tree.") from e
    booster = _to_booster(booster)
    if tree_index < 0 or tree_index >= len(booster.models):
        raise IndexError("tree_index is out of range.")
    t = booster.models[tree_index]
    names = booster.feature_name()
    show_info = show_info or []

    graph = Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr("graph", nodesep="0.05", ranksep="0.3", rankdir=rankdir)

    def leaf_label(i):
        parts = [f"leaf {i}: {t.leaf_value[i]:.{precision}f}"]
        if "leaf_count" in show_info and len(t.leaf_count) > i:
            parts.append(f"count: {int(t.leaf_count[i])}")
        if "leaf_weight" in show_info and len(t.leaf_weight) > i:
            parts.append(f"weight: {t.leaf_weight[i]:.{precision}f}")
        return "\n".join(parts)

    def add(node_idx):
        name = f"node{node_idx}"
        f = int(t.split_feature[node_idx])
        d = int(t.decision_type[node_idx])
        if d & 1:
            cond = f"{names[f]} in categories"
        else:
            cond = f"{names[f]} <= {t.threshold[node_idx]:.{precision}f}"
        parts = [cond]
        if "split_gain" in show_info:
            parts.append(f"gain: {t.split_gain[node_idx]:.{precision}f}")
        if "internal_count" in show_info:
            parts.append(f"count: {int(t.internal_count[node_idx])}")
        graph.node(name, "\n".join(parts), shape="rectangle")
        for child, tag in ((int(t.left_child[node_idx]), "yes"),
                           (int(t.right_child[node_idx]), "no")):
            if child < 0:
                leaf = ~child
                cname = f"leaf{leaf}"
                graph.node(cname, leaf_label(leaf), shape="ellipse")
            else:
                cname = f"node{child}"
                add(child)
            graph.edge(name, cname, label=tag)

    if t.num_internal == 0:
        graph.node("leaf0", leaf_label(0), shape="ellipse")
    else:
        add(0)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info: Optional[List[str]] = None, precision: int = 3,
              orientation: str = "horizontal", **kwargs):
    """Render one tree with matplotlib via graphviz
    (ref: plotting.py plot_tree)."""
    try:
        import matplotlib.image as mimage
        import matplotlib.pyplot as plt
    except ImportError as e:
        raise ImportError("You must install matplotlib to plot tree.") from e
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    from io import BytesIO
    buf = BytesIO(graph.pipe(format="png"))
    img = mimage.imread(buf)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.imshow(img)
    ax.axis("off")
    return ax
