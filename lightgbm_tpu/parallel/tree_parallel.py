"""Feature-parallel and voting-parallel tree learners over a device mesh.

TPU-native analogs of the reference's distributed learner wrappers:

- feature-parallel (ref: src/treelearner/feature_parallel_tree_learner.cpp):
  every shard holds the full row set but only histograms/scans its own
  column slice; the per-level best splits are merged with a pmax +
  winner-shard pick (the SyncUpGlobalBestSplit allreduce of 48-byte
  SplitInfo records, parallel_tree_learner.h:191-214). Zero histogram
  traffic — the only comm is [num_leaves]-sized split records.

- voting-parallel (ref: src/treelearner/voting_parallel_tree_learner.cpp):
  rows sharded as in data-parallel, but instead of allreducing the full
  [L, F, B, 3] histogram each level, shards vote for their local top_k
  features and only the 2*top_k winners' columns are summed — the level
  payload drops from F*B*3 to 2*top_k*B*3 (GlobalVoting/CopyLocalHistogram
  :151-184). Divergence from the reference, documented in
  models/learner.py: winners are the per-LEVEL union of slot votes.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.learner import FeatureMeta, grow_tree_depthwise
from ..models.tree import TreeArrays
from ..ops.split import SplitParams
from .mesh import DATA_AXIS, shard_map

FEATURE_AXIS = "feature"


def pad_features(F: int, n_shards: int) -> int:
    return ((F + n_shards - 1) // n_shards) * n_shards


def make_feature_parallel_grow_fn(mesh: Mesh, params: SplitParams,
                                  num_leaves: int, max_bins: int,
                                  max_depth: int = -1,
                                  hist_impl: str = "auto",
                                  axis_name: str = FEATURE_AXIS,
                                  has_cat: bool = False):
    """Feature-parallel growth: bins column-sharded for histogram work,
    replicated for routing.

    The jitted fn takes (bins [R, Fp] REPLICATED, gh [R, 3] replicated,
    meta over Fp features, feature_mask [Fp]) and returns (tree with
    GLOBAL feature indices, row_leaf [R]). Fp must divide evenly by the
    mesh axis size (pad trivial features and mask them off).
    """
    n_shards = mesh.shape[axis_name]

    def per_shard(bins_full, gh, meta, feature_mask):
        Fp = bins_full.shape[1]
        Fs = Fp // n_shards
        sid = jax.lax.axis_index(axis_name)
        f0 = sid * Fs
        bins_loc = jax.lax.dynamic_slice_in_dim(bins_full, f0, Fs, axis=1)
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, f0, Fs, axis=0)
        meta_loc = FeatureMeta(
            num_bin=sl(meta.num_bin), missing_type=sl(meta.missing_type),
            default_bin=sl(meta.default_bin), monotone=sl(meta.monotone),
            is_cat=None if meta.is_cat is None else sl(meta.is_cat))
        mask_loc = sl(feature_mask)
        return grow_tree_depthwise(
            bins_loc, gh, meta_loc, mask_loc, params, num_leaves, max_bins,
            max_depth, hist_impl=hist_impl, psum_axis=axis_name,
            has_cat=has_cat, parallel_mode="feature",
            route_bins=bins_full, route_meta=meta, feature_offset=f0)

    sharded = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False)
    return jax.jit(sharded)


def make_voting_parallel_grow_fn(mesh: Mesh, params: SplitParams,
                                 num_leaves: int, max_bins: int,
                                 max_depth: int = -1, top_k: int = 20,
                                 hist_impl: str = "auto",
                                 axis_name: str = DATA_AXIS):
    """Voting-parallel growth: rows sharded; per-level histogram exchange
    restricted to the 2*top_k vote-winning features."""
    def per_shard(bins, gh, meta, feature_mask):
        return grow_tree_depthwise(
            bins, gh, meta, feature_mask, params, num_leaves, max_bins,
            max_depth, hist_impl=hist_impl, psum_axis=axis_name,
            parallel_mode="voting", top_k=top_k)

    sharded = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name, None), P(), P()),
        out_specs=(P(), P(axis_name)),
        check_vma=False)
    return jax.jit(sharded)


# 48 bytes: the reference's allreduced SplitInfo record
# (parallel_tree_learner.h:191-214 SyncUpGlobalBestSplit)
_SPLIT_RECORD_BYTES = 48


def feature_collective_profile(num_leaves: int,
                               max_depth_grown: int = None
                               ) -> Tuple[int, int]:
    """(count, bytes) estimate of one tree's feature-parallel exchange:
    zero histogram traffic, one best-split-record merge per level (the
    SyncUpGlobalBestSplit analog; here a pmax over [L]-sized records).
    Levels default to ceil(log2(num_leaves)) + 1 for a balanced tree."""
    import math
    L = max(2, int(num_leaves))
    levels = (int(max_depth_grown) if max_depth_grown
              else int(math.ceil(math.log2(L))) + 1)
    return levels, levels * L * _SPLIT_RECORD_BYTES


def voting_collective_profile(num_leaves: int, num_features: int,
                              max_bins: int, top_k: int) -> Tuple[int, int]:
    """(count, bytes) estimate of one tree's voting-parallel exchange:
    per histogrammed node, a [F] int32 vote psum plus the 2*top_k
    winning features' [B, 3] f32 histogram columns
    (voting_parallel_tree_learner.cpp:151-184 GlobalVoting +
    CopyLocalHistogram). Fallback only since round 12 — see
    data_parallel.collective_profile on the measured recorder that
    supersedes these estimates on every traced-grower path."""
    node_hists = max(1, int(num_leaves))
    per_node = (int(num_features) * 4
                + 2 * int(top_k) * int(max_bins) * 3 * 4)
    return 2 * node_hists, node_hists * per_node
