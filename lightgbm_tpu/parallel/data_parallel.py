"""Data-parallel tree growth: rows sharded, histograms allreduced.

TPU-native analog of ref: src/treelearner/data_parallel_tree_learner.cpp.
The reference reduce-scatters byte-laid-out histograms so each rank owns the
globally-summed histograms of a feature subset, finds its best split, then
allreduce-maxes 48-byte SplitInfo records (:155-260).  On an ICI mesh the
whole exchange is one `psum` of the histogram tensor inside the jit-compiled
grow loop — each shard then computes the identical global argmax locally, so
no second sync is needed (split decisions are replicated by construction).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.learner import (FeatureMeta, grow_tree_depthwise,
                              grow_tree_leafwise)
from ..models.tree import TreeArrays
from ..ops.split import SplitParams
from .mesh import DATA_AXIS


def make_sharded_grow_fn(mesh: Mesh, params: SplitParams, num_leaves: int,
                         max_bins: int, max_depth: int = -1,
                         policy: str = "leafwise", hist_impl: str = "auto",
                         axis_name: str = DATA_AXIS,
                         has_cat: bool = False,
                         use_mono_bounds: bool = False,
                         use_node_masks: bool = False, node_masks=None,
                         n_forced: int = 0, forced_leaf=None,
                         forced_feat=None, forced_thr=None):
    """shard_map-wrapped tree growth: bins/gh row-sharded in, replicated tree
    + row-sharded leaf assignment out. ``has_cat`` enables the categorical
    split scan (pass True whenever the dataset has categorical features —
    without it category bins would be scanned as ordered numeric
    thresholds)."""
    grow = grow_tree_leafwise if policy == "leafwise" else grow_tree_depthwise

    def per_shard(bins, gh, meta, feature_mask):
        return grow(bins, gh, meta, feature_mask, params, num_leaves,
                    max_bins, max_depth, hist_impl=hist_impl,
                    psum_axis=axis_name, has_cat=has_cat,
                    use_mono_bounds=use_mono_bounds,
                    use_node_masks=use_node_masks, node_masks=node_masks,
                    **({"n_forced": n_forced, "forced_leaf": forced_leaf,
                        "forced_feat": forced_feat,
                        "forced_thr": forced_thr}
                       if policy == "leafwise" and n_forced else {}))

    sharded = jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name, None), P(), P()),
        out_specs=(P(), P(axis_name)),
        check_vma=False)
    return jax.jit(sharded)


def grow_tree_data_parallel(mesh: Mesh, bins, gh, meta: FeatureMeta,
                            feature_mask, params: SplitParams,
                            num_leaves: int, max_bins: int,
                            max_depth: int = -1, policy: str = "leafwise",
                            hist_impl: str = "auto", has_cat: bool = False,
                            ) -> Tuple[TreeArrays, jax.Array]:
    """One-shot helper (the GBDT driver caches make_sharded_grow_fn)."""
    fn = make_sharded_grow_fn(mesh, params, num_leaves, max_bins, max_depth,
                              policy, hist_impl, has_cat=has_cat)
    return fn(bins, gh, meta, feature_mask)


def train_step_data_parallel(mesh: Mesh, params: SplitParams,
                             num_leaves: int, max_bins: int,
                             axis_name: str = DATA_AXIS,
                             policy: str = "depthwise",
                             has_cat: bool = False):
    """A FULL jit-compiled data-parallel boosting step: binary-logloss
    gradients -> sharded tree growth (histogram psum over the mesh) -> score
    update.  This is the flagship multi-chip path the driver dry-runs
    (ref call stack being replaced: gbdt.cpp:371 TrainOneIter +
    data_parallel_tree_learner.cpp FindBestSplits).

    Returns a jitted fn: (bins[R,F] sharded, label[R] sharded,
    valid[R] sharded, score[R] sharded, meta, feature_mask) ->
    (new_score, tree arrays).  ``valid`` is 1.0 for real rows, 0.0 for
    shard_rows padding — padded rows must carry zero histogram weight.
    """
    grow = grow_tree_leafwise if policy == "leafwise" else grow_tree_depthwise

    def per_shard(bins, label, valid, score, meta, feature_mask):
        # gradients: binary logloss (ref: binary_objective.hpp:107)
        lv = jnp.where(label > 0, 1.0, -1.0)
        response = -lv / (1.0 + jnp.exp(lv * score))
        grad = response * valid
        hess = jnp.abs(response) * (1.0 - jnp.abs(response)) * valid
        gh = jnp.stack([grad, hess, valid], axis=1)
        tree, row_leaf = grow(bins, gh, meta, feature_mask, params,
                              num_leaves, max_bins, -1,
                              hist_impl="segment", psum_axis=axis_name,
                              has_cat=has_cat)
        new_score = score + 0.1 * tree.leaf_value[row_leaf]
        return new_score, tree

    sharded = jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name), P(axis_name),
                  P(axis_name), P(), P()),
        out_specs=(P(axis_name), P()),
        check_vma=False)
    return jax.jit(sharded)
