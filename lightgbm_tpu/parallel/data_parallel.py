"""Data-parallel tree growth: rows sharded, histograms allreduced.

TPU-native analog of ref: src/treelearner/data_parallel_tree_learner.cpp.
The reference reduce-scatters byte-laid-out histograms so each rank owns the
globally-summed histograms of a feature subset, finds its best split, then
allreduce-maxes 48-byte SplitInfo records (:155-260).  On an ICI mesh the
whole exchange is one `psum` of the histogram tensor inside the jit-compiled
grow loop — each shard then computes the identical global argmax locally, so
no second sync is needed (split decisions are replicated by construction).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.learner import (FeatureMeta, grow_tree_depthwise,
                              grow_tree_leafwise)
from ..models.tree import TreeArrays
from ..ops.split import SplitParams
from .mesh import DATA_AXIS, shard_map


def make_sharded_grow_fn(mesh: Mesh, params: SplitParams, num_leaves: int,
                         max_bins: int, max_depth: int = -1,
                         policy: str = "leafwise", hist_impl: str = "auto",
                         axis_name: str = DATA_AXIS,
                         has_cat: bool = False,
                         use_mono_bounds: bool = False,
                         use_node_masks: bool = False, node_masks=None,
                         n_forced: int = 0, forced_leaf=None,
                         forced_feat=None, forced_thr=None):
    """shard_map-wrapped tree growth: bins/gh row-sharded in, replicated tree
    + row-sharded leaf assignment out. ``has_cat`` enables the categorical
    split scan (pass True whenever the dataset has categorical features —
    without it category bins would be scanned as ordered numeric
    thresholds).

    Role: the STANDALONE composition surface (unit tests, external
    embedders growing single trees). The product driver builds its own
    richer closures (bundles, CEGB, node masks, feature slicing) in
    GBDT._build_par_fn — but both delegate to the same grow_tree_*
    functions, where the psum collectives live exactly once (round-3
    review: the driver and these factories must not carry divergent
    copies of the collective logic; they don't — neither implements
    any)."""
    grow = grow_tree_leafwise if policy == "leafwise" else grow_tree_depthwise

    def per_shard(bins, gh, meta, feature_mask):
        return grow(bins, gh, meta, feature_mask, params, num_leaves,
                    max_bins, max_depth, hist_impl=hist_impl,
                    psum_axis=axis_name, has_cat=has_cat,
                    use_mono_bounds=use_mono_bounds,
                    use_node_masks=use_node_masks, node_masks=node_masks,
                    **({"n_forced": n_forced, "forced_leaf": forced_leaf,
                        "forced_feat": forced_feat,
                        "forced_thr": forced_thr}
                       if policy == "leafwise" and n_forced else {}))

    sharded = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name, None), P(), P()),
        out_specs=(P(), P(axis_name)),
        check_vma=False)
    return jax.jit(sharded)


def collective_profile(num_leaves: int, num_features: int, max_bins: int,
                       leafwise: bool = True) -> Tuple[int, int]:
    """(count, bytes) estimate of one tree's in-jit histogram allreduce
    traffic under data-parallel growth, for the telemetry registry.

    Since round 12 the registry records MEASURED traffic instead (every
    grower psum/pmax routes through ops/collectives.record_psum, whose
    trace-time recorder captures the real lowered shapes at the first
    call of each fresh jit) — this analytic model remains only as the
    documented fallback for paths that never traced a grower.

    The exchange is the reference's reduce-scatter of [F, B, 3] f32
    histograms (data_parallel_tree_learner.cpp:155-189), collapsed here
    into one ``psum`` per histogrammed node: leaf-wise growth histograms
    the root plus one child per split (the sibling is derived by
    subtraction); depth-wise growth histograms every non-derived node of
    every level — both are ~``num_leaves`` node histograms per tree.
    Analytic payload of the lowered collectives, not a wire measurement
    (XLA may fuse or reduce-scatter under the hood)."""
    node_hists = max(1, int(num_leaves))
    hist_bytes = int(num_features) * int(max_bins) * 3 * 4
    return node_hists, node_hists * hist_bytes
