"""External collective-function injection (ref: c_api.h:1336
LGBM_NetworkInitWithFunctions -> Network::ExternalInit, meta.h:68
ReduceScatterFunction/AllgatherFunction typedefs).

Embedders that own their transport (MPI wrappers, Spark barrier
executors) hand the reference two C function pointers and every
collective rides them. The TPU analog keeps that contract for the
HOST-side collectives (metadata/model/statistic sync — the traffic the
reference routes through these functions between tree levels), exposed
here as numpy-array allgather/reduce-scatter wrappers with the exact C
calling convention. DEVICE-side histogram collectives are in-jit XLA
psums over the jax.distributed mesh — an external function pointer
cannot be spliced into an XLA collective schedule, so multi-process
training additionally needs the jax process runtime up
(`parallel.distributed.init_distributed` / the launcher); registering
external functions alone coordinates the host plane only.

C signatures marshaled (comm_size_t = int32):
  void reduce_scatter(char* input, int32 input_size, int type_size,
                      const int32* block_start, const int32* block_len,
                      int num_block, char* output, int32 output_size,
                      const ReduceFunction& reducer)
  void allgather(char* input, int32 input_size,
                 const int32* block_start, const int32* block_len,
                 int num_block, char* output, int32 output_size)
  void reducer(const char* input, char* output, int type_size,
               int32 array_size)
"""
from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from ..utils import log

_c_i32 = ctypes.c_int32
# src/dst as void* (not char*): ctypes converts c_char_p callback args to
# immutable bytes, which would break in-place reduction
_REDUCE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p,
                              ctypes.c_int, _c_i32)
_REDUCE_SCATTER_FN = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, _c_i32, ctypes.c_int,
    ctypes.POINTER(_c_i32), ctypes.POINTER(_c_i32), ctypes.c_int,
    ctypes.c_void_p, _c_i32, ctypes.POINTER(_REDUCE_FN))
_ALLGATHER_FN = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, _c_i32, ctypes.POINTER(_c_i32),
    ctypes.POINTER(_c_i32), ctypes.c_int, ctypes.c_void_p, _c_i32)


class _ExtNet:
    def __init__(self, num_machines: int, rank: int,
                 reduce_scatter_addr: int, allgather_addr: int):
        self.num_machines = num_machines
        self.rank = rank
        self.reduce_scatter_fn = _REDUCE_SCATTER_FN(reduce_scatter_addr)
        self.allgather_fn = _ALLGATHER_FN(allgather_addr)


_STATE: Optional[_ExtNet] = None


def init_with_functions(num_machines: int, rank: int,
                        reduce_scatter_addr: int,
                        allgather_addr: int) -> None:
    global _STATE
    if num_machines < 1 or not (0 <= rank < num_machines):
        raise ValueError(f"invalid rank {rank} of {num_machines} machines")
    if num_machines > 1 and (not reduce_scatter_addr or not allgather_addr):
        raise ValueError("NetworkInitWithFunctions needs both function "
                         "pointers for num_machines > 1")
    _STATE = _ExtNet(num_machines, rank, reduce_scatter_addr or 0,
                     allgather_addr or 0)
    log.info("external network functions registered: rank %d of %d",
             rank, num_machines)


def free() -> None:
    global _STATE
    _STATE = None


def is_active() -> bool:
    return _STATE is not None and _STATE.num_machines > 1


def rank() -> int:
    return _STATE.rank if _STATE is not None else 0


def num_machines() -> int:
    return _STATE.num_machines if _STATE is not None else 1


def allgather(local: np.ndarray) -> np.ndarray:
    """Every rank's ``local`` block -> concatenated array, identical on
    all ranks. Blocks must be the same shape on every rank (the
    fixed-block layout Network::Allgather uses for same-size payloads)."""
    st = _STATE
    if st is None or st.num_machines == 1:
        return np.asarray(local).copy()
    loc = np.ascontiguousarray(local)
    bs = loc.nbytes
    n = st.num_machines
    starts = (_c_i32 * n)(*[i * bs for i in range(n)])
    lens = (_c_i32 * n)(*[bs] * n)
    out = np.empty(n * bs, np.uint8)
    st.allgather_fn(
        loc.ctypes.data_as(ctypes.c_void_p), _c_i32(bs), starts, lens,
        ctypes.c_int(n), out.ctypes.data_as(ctypes.c_void_p),
        _c_i32(out.nbytes))
    return out.view(loc.dtype).reshape((n * loc.shape[0],) + loc.shape[1:])


def _sum_reducer_for(dtype: np.dtype) -> _REDUCE_FN:
    dt = np.dtype(dtype)

    def _reduce(src, dst, type_size, array_size):
        nelem = array_size // dt.itemsize

        def as_np(addr):
            return np.ctypeslib.as_array(
                ctypes.cast(addr, ctypes.POINTER(ctypes.c_uint8)),
                shape=(array_size,)).view(dt)[:nelem]
        b = as_np(dst)
        b += as_np(src)
    return _REDUCE_FN(_reduce)


def allreduce_sum(local: np.ndarray) -> np.ndarray:
    """Sum-allreduce built the reference way: reduce-scatter (external
    function + injected sum reducer) then allgather of the owned block
    (ref: network.cpp Network::Allreduce decomposition)."""
    st = _STATE
    if st is None or st.num_machines == 1:
        return np.asarray(local).copy()
    loc = np.ascontiguousarray(local)
    dt, shape = loc.dtype, loc.shape
    flat = loc.reshape(-1)
    n = st.num_machines
    # pad so every rank owns an equal block of whole elements
    per = -(-flat.size // n)
    padded = np.zeros(per * n, dt)
    padded[:flat.size] = flat
    bs = per * dt.itemsize
    starts = (_c_i32 * n)(*[i * bs for i in range(n)])
    lens = (_c_i32 * n)(*[bs] * n)
    own = np.zeros(per, dt)
    reducer = _sum_reducer_for(dt)
    st.reduce_scatter_fn(
        padded.ctypes.data_as(ctypes.c_void_p), _c_i32(padded.nbytes),
        ctypes.c_int(dt.itemsize), starts, lens, ctypes.c_int(n),
        own.ctypes.data_as(ctypes.c_void_p), _c_i32(own.nbytes),
        ctypes.pointer(reducer))
    return allgather(own)[:flat.size].reshape(shape)
