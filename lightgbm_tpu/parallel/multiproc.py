"""Multi-process (multi-host) data-parallel training support.

The reference trains ONE model across N machines by running N processes
joined through its socket/MPI Network: each rank loads a disjoint row
shard, histograms are reduce-scattered, split decisions replicated
(ref: src/treelearner/data_parallel_tree_learner.cpp:126-276, proven by
tests/distributed/_test_distributed.py:170-198). The TPU-native analog:
``jax.distributed.initialize()`` gives every process the GLOBAL device
mesh; per-rank shards become one global row-sharded ``jax.Array``; the
in-jit ``psum`` collectives then span processes over ICI/DCN exactly as
they span local devices — no transport layer of our own.

Layout contract (rank-blocked padded rows):
- every process owns ``block = S * local_device_count`` consecutive rows
  of the padded global space, its real rows first;
- pad rows carry ZERO weight everywhere (the same zero-weight-pad
  contract the single-process parallel path already uses), enforced by
  folding ``real_mask`` into the bagging weight vector and into the
  metadata weight column;
- host-side per-row state (labels, weights, bagging draws, feature
  masks) is allgathered or recomputed IDENTICALLY on every rank, so all
  ranks run the same Python program on the same values — the SPMD
  contract that makes every rank emit the identical model.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import log


class GlobalMetadata:
    """Host-side global view of per-row metadata, identical on every
    rank (the driver re-inits objectives/metrics with this so their
    statistics — label means, class counts, metric weights — are global,
    matching the reference's Network::GlobalSyncUp* paths).

    Ranking: ``query_boundaries`` is cumulative over the COMPACTED real
    rows (total_real), and ``query_row_map`` [total_real] maps each
    compacted row to its PADDED global row index (rank blocks leave
    gaps); consumers index label/weight/scores through the map. The
    loader guarantees queries never straddle ranks
    (ref: metadata.cpp:141 CheckOrPartition)."""

    def __init__(self, label, weight, init_score, query_boundaries=None,
                 query_row_map=None):
        self.label = label
        self.weight = weight
        self.init_score = init_score
        self.query_boundaries = query_boundaries
        self.query_row_map = query_row_map


class MultiProcLayout:
    """Row layout + placement helpers for one global mesh."""

    def __init__(self, mesh: Mesh, axis: str, local_rows: int,
                 row_align: int = 1, telemetry=None):
        from jax.experimental import multihost_utils

        self._mh = multihost_utils
        # host-plane collective accounting: every process_allgather this
        # layout performs is counted for real (count + payload bytes)
        # into the driver's telemetry registry, rank-tagged there
        self.telemetry = telemetry
        self.mesh = mesh
        self.axis = axis
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        devs = list(mesh.devices.flat)
        self.n_dev = len(devs)
        self.dev_per_proc = sum(
            1 for d in devs if d.process_index == self.process_index)
        if self.dev_per_proc * self.process_count != self.n_dev:
            log.fatal("multi-process training needs the same device count "
                      "on every process (got %d local of %d total over %d "
                      "processes)", self.dev_per_proc, self.n_dev,
                      self.process_count)
        # the rank-blocked layout contract: mesh-axis position r*dpp..(r+1)
        # *dpp must belong to process r, or shard_local would place rank
        # r's binned rows against ANOTHER rank's block of the allgathered
        # labels/real-mask — silent mistraining, so verify, don't assume
        for r in range(self.process_count):
            blk = devs[r * self.dev_per_proc:(r + 1) * self.dev_per_proc]
            if any(d.process_index != blk[0].process_index for d in blk) \
                    or blk[0].process_index != r:
                log.fatal("mesh devices are not grouped in ascending "
                          "process order along the data axis (position "
                          "%d holds process %d); build the mesh from "
                          "jax.devices() order", r * self.dev_per_proc,
                          blk[0].process_index)
        self.local_real = int(local_rows)
        counts = np.asarray(self._allgather(
            np.asarray([self.local_real], np.int64))).reshape(-1)
        self.counts = [int(c) for c in counts]
        self.total_real = int(sum(self.counts))
        # rows per device: every rank's shard must fit its block;
        # row_align > 1 (the fused kernel's widest tile) keeps every
        # per-device slice kernel-tile-divisible — pad rows carry zero
        # weight everywhere, so alignment only costs memory
        self.S = max(1, -(-max(self.counts) // self.dev_per_proc))
        if row_align > 1:
            self.S = ((self.S + row_align - 1) // row_align) * row_align
        self.block = self.S * self.dev_per_proc
        self.Np = self.S * self.n_dev
        log.info("multi-process layout: %d processes x %d devices, "
                 "%d real rows -> %d padded (%d rows/device)",
                 self.process_count, self.dev_per_proc, self.total_real,
                 self.Np, self.S)

    # ------------------------------------------------------------ host
    def _allgather(self, arr: np.ndarray):
        """process_allgather with telemetry accounting (real payloads,
        not estimates: count 1, bytes = gathered result size) — timed,
        so the trace timeline shows each host-plane collective as a real
        span on the rank's collectives track. Guarded: with
        ``collective_timeout`` configured, a hung peer raises a
        structured CollectiveError instead of deadlocking the layout
        (resilience/comms.py)."""
        from ..resilience.comms import guarded_call
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return guarded_call(lambda: self._mh.process_allgather(arr),
                                what="mp_allgather")
        wall0 = tel.wall_now()
        t0 = time.perf_counter()
        out = guarded_call(lambda: self._mh.process_allgather(arr),
                           what="mp_allgather", telemetry=tel)
        dt = time.perf_counter() - t0
        a = np.asarray(arr)
        tel.collective("host_allgather", 1,
                       int(a.size) * int(a.dtype.itemsize)
                       * int(self.process_count),
                       seconds=dt, wall_start=wall0)
        return out

    def pad_local(self, arr: np.ndarray) -> np.ndarray:
        """[local_real, ...] -> [block, ...] zero-padded."""
        arr = np.asarray(arr)
        pad = self.block - arr.shape[0]
        if pad < 0:
            log.fatal("local shard has %d rows but the block is %d",
                      arr.shape[0], self.block)
        if pad == 0:
            return arr
        return np.pad(arr, [(0, pad)] + [(0, 0)] * (arr.ndim - 1))

    def allgather_rows(self, local: Optional[np.ndarray],
                      fill=0) -> Optional[np.ndarray]:
        """Per-rank local rows -> identical [Np, ...] host array on every
        rank (the mapper-allgather pattern of dataset_loader.cpp:1146
        applied to metadata columns)."""
        if local is None:
            return None
        loc = self.pad_local(np.asarray(local))
        if fill != 0:
            loc[self.local_real:] = fill
        out = np.asarray(self._allgather(loc))
        return out.reshape((self.Np,) + loc.shape[1:])

    def real_mask_np(self) -> np.ndarray:
        """[Np] f32: 1.0 for real rows, 0.0 for pads."""
        m = np.zeros((self.Np,), np.float32)
        for r, c in enumerate(self.counts):
            off = r * self.block
            m[off:off + c] = 1.0
        return m

    def global_metadata(self, md) -> GlobalMetadata:
        """Global host metadata from the rank-local one. The weight
        column always exists afterwards (real_mask when the data is
        unweighted) so pad rows carry zero weight through objectives and
        metrics."""
        qb_global = None
        qmap = None
        if getattr(md, "query_boundaries", None) is not None:
            # per-rank query sizes -> global compacted boundaries + the
            # compacted-row -> padded-global-row map (rank r's rows live
            # at [r*block, r*block + counts[r]))
            sizes = np.diff(np.asarray(md.query_boundaries, np.int64))
            nq = np.asarray(self._allgather(
                np.asarray([sizes.size], np.int64))).reshape(-1)
            m = int(nq.max())
            pad = np.zeros(m, np.int64)
            pad[:sizes.size] = sizes
            allq = np.asarray(self._allgather(pad)) \
                .reshape(self.process_count, m)
            all_sizes = np.concatenate(
                [allq[r, :int(nq[r])] for r in range(self.process_count)])
            qb_global = np.concatenate(
                [[0], np.cumsum(all_sizes)]).astype(np.int64)
            qmap = np.concatenate(
                [r * self.block + np.arange(self.counts[r], dtype=np.int64)
                 for r in range(self.process_count)])
            if int(qb_global[-1]) != self.total_real:
                log.fatal("query sizes sum to %d but the global data has "
                          "%d real rows — query-aligned sharding was "
                          "violated", int(qb_global[-1]), self.total_real)
        label = self.allgather_rows(md.label)
        weight = self.allgather_rows(md.weight)
        mask = self.real_mask_np()
        weight = mask if weight is None else weight * mask
        init_score = md.init_score
        if init_score is not None:
            init_score = np.asarray(init_score)
            if init_score.ndim == 1 and init_score.size != self.local_real:
                # per-class flattened layout [k*n]: gather per class
                k = init_score.size // self.local_real
                cols = init_score.reshape(k, self.local_real)
                init_score = np.concatenate(
                    [self.allgather_rows(c) for c in cols])
            else:
                init_score = self.allgather_rows(init_score)
        return GlobalMetadata(label, weight, init_score,
                              query_boundaries=qb_global,
                              query_row_map=qmap)

    def local_block(self, garr: jax.Array, axis: int = 0) -> np.ndarray:
        """This rank's block of a row-sharded global array, in device
        order ([block, ...] for axis=0; [..., block] for axis=1) — the
        host-side view for rank-local work (renewal percentiles, GOSS
        thresholds) the reference also keeps machine-local. Handles
        REPLICATED arrays too (e.g. a constant-hessian objective's
        broadcast ones): duplicates are deduped by slice start and a
        full-axis result is cut down to this rank's block."""
        shards = [s for s in garr.addressable_shards]
        seen = {}
        for s in shards:
            st = s.index[axis].start or 0
            seen.setdefault(st, s)
        parts = [np.asarray(seen[k].data) for k in sorted(seen)]
        out = np.concatenate(parts, axis=axis)
        if out.shape[axis] == garr.shape[axis] \
                and garr.shape[axis] == self.Np:
            off = self.process_index * self.block
            sl = [slice(None)] * out.ndim
            sl[axis] = slice(off, off + self.block)
            return out[tuple(sl)]
        return out

    def shard_local_cols(self, loc: np.ndarray) -> jax.Array:
        """Per-rank [k, block] column block -> global [k, Np] sharded on
        the row axis (axis 1) — the gradient layout."""
        sh = NamedSharding(self.mesh, P(None, self.axis))
        return jax.make_array_from_process_local_data(sh, loc)

    # ---------------------------------------------------------- device
    def shard_local(self, local: np.ndarray) -> jax.Array:
        """Per-rank local rows -> global row-sharded jax.Array (the only
        placement that moves per-rank-DISTINCT data; everything else is
        replicated host state)."""
        loc = self.pad_local(np.asarray(local))
        sh = NamedSharding(self.mesh,
                           P(self.axis, *([None] * (loc.ndim - 1))))
        return jax.make_array_from_process_local_data(sh, loc)

    def shard_full(self, full: np.ndarray, spec: P = None) -> jax.Array:
        """Identical full-size host array on every rank -> sharded global
        array (each process donates only its addressable slices)."""
        full = np.asarray(full)
        if spec is None:
            spec = P(self.axis, *([None] * (full.ndim - 1)))
        sh = NamedSharding(self.mesh, spec)
        return jax.make_array_from_callback(
            full.shape, sh, lambda idx: full[idx])

    def zeros_sharded(self, shape, spec: P, dtype=jnp.float32) -> jax.Array:
        return jax.jit(lambda: jnp.zeros(shape, dtype),
                       out_shardings=NamedSharding(self.mesh, spec))()
