"""Mesh construction helpers.

The reference manages machine lists, ports and socket meshes
(ref: src/network/linkers_socket.cpp:81-189); on TPU the topology is XLA's
problem — we just name axes on a device mesh (jax-ml.github.io/scaling-book
recipe: pick a mesh, annotate shardings, let XLA insert collectives).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import log

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Version-portable shard_map: ``jax.shard_map`` where it exists
    (jax >= 0.6), else ``jax.experimental.shard_map.shard_map`` whose
    replication check carries the older ``check_rep`` name. Every
    shard_map in the tree learners routes through here so a jax upgrade
    is a one-line change."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def buffer_donation_supported() -> bool:
    """True where XLA actually honors ``donate_argnums`` (TPU/GPU).
    The CPU backend copies anyway and warns per lowering, so callers
    request donation only where it is real. Donation composes with
    sharded operands too: a row-sharded score matrix under a multi-
    process layout donates per-shard buffers, so the in-place update
    holds on every rank."""
    try:
        return jax.default_backend() in ("tpu", "gpu")
    except Exception:
        return False


def donate_argnums(*argnums: int):
    """``donate_argnums`` tuple for jax.jit, empty off-TPU/GPU — the
    one-line idiom every driver jit that re-writes its score/gradient
    carry buffers routes through (boosting/gbdt.py fast path, megastep,
    epilogue, valid updates, parallel growers)."""
    return tuple(argnums) if buffer_donation_supported() else ()


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over available devices (the data-parallel default).

    Multi-host: call after jax.distributed.initialize(); jax.devices()
    spans the pod slice and the same code shards over ICI+DCN.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            log.fatal("Requested %d devices but only %d available "
                      "(set XLA_FLAGS=--xla_force_host_platform_device_count "
                      "for virtual CPU devices)", n_devices, len(devices))
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def make_mesh_2d(n_data: int, n_feature: int) -> Mesh:
    """2-D mesh for combined data × feature sharding."""
    devices = jax.devices()
    need = n_data * n_feature
    if len(devices) < need:
        log.fatal("Requested %dx%d mesh but only %d devices", n_data,
                  n_feature, len(devices))
    arr = np.asarray(devices[:need]).reshape(n_data, n_feature)
    return Mesh(arr, (DATA_AXIS, FEATURE_AXIS))


def shard_rows(mesh: Mesh, array, axis_name: str = DATA_AXIS,
               pad_value=0):
    """Place a host array row-sharded on the mesh, padding rows to a multiple
    of the shard count (the pad rows carry zero weight downstream)."""
    n = array.shape[0]
    d = mesh.shape[axis_name]
    rem = (-n) % d
    if rem:
        pad_width = [(0, rem)] + [(0, 0)] * (array.ndim - 1)
        array = np.pad(np.asarray(array), pad_width,
                       constant_values=pad_value)
    spec = P(axis_name, *([None] * (array.ndim - 1)))
    return jax.device_put(array, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, array):
    return jax.device_put(array, NamedSharding(mesh, P()))
