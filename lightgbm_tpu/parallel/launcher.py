"""Multi-process training launcher — the orchestration analog of the
reference's Dask integration, plus supervised fault recovery.

The reference's ``dask.py`` finds open ports, builds the ``machines``
string, runs one local fit per worker, and returns rank 0's booster
(ref: python-package/lightgbm/dask.py:67-135 port negotiation, :166
``_train_part``, :392 ``_train``). On the JAX runtime the transport
negotiation collapses to ``jax.distributed.initialize`` against one
coordinator address; this module supplies the remaining orchestration:
spawn N processes, give each its rank, let each load its shard of the
data file (the loader reads per-rank row slices and allgathers the
binning sample), train ONE model jointly (``tree_learner=data`` over
the global mesh — parallel/multiproc.py), and hand back rank 0's
booster.

**Elastic recovery** (docs/Reliability.md): XLA collectives make one
rank's crash fatal to the cohort, so the launcher supervises — it polls
the workers, and when any rank dies it kills the rest, selects the
newest checkpoint that is complete and hash-consistent across ALL
ranks (``resilience.checkpoint.select_checkpoint``), and respawns the
cohort resuming from it, with capped retries and exponential backoff.
With ``checkpoint_period=N`` the lost work is bounded by N iterations;
the final model is bit-identical to an uninterrupted run.

Single-host by default (N local processes, gloo collectives on CPU or
one process per accelerator); multi-host works by running the same
worker on every host with ``coordinator_address`` pointing at host 0 —
the exact shape of the reference's machine-list deployments.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional

from ..resilience.checkpoint import select_checkpoint
from ..resilience.faults import FAULT_STATE_ENV
from ..utils import log

_WORKER = """
import json, os, sys
cfg = json.load(open(sys.argv[1]))
import jax
if cfg["env"].get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=cfg["coordinator"],
    num_processes=cfg["num_processes"], process_id=cfg["rank"])
import lightgbm_tpu as lgb

ds = lgb.Dataset(cfg["data"], params=cfg["dataset_params"])
bst = lgb.train(cfg["params"], ds,
                num_boost_round=cfg["num_boost_round"],
                resume_from=cfg.get("resume") or None)
if jax.process_index() == 0:
    with open(cfg["out"], "w") as fh:
        fh.write(bst.model_to_string(num_iteration=-1))
"""


def _free_port() -> int:
    # NOTE: inherently racy (the socket closes before the coordinator
    # rebinds); SO_REUSEADDR narrows the window. Contended environments
    # should pass coordinator_address explicitly.
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_cohort(td, script, params, data_path, num_processes,
                  num_boost_round, dataset_params, out, coord,
                  devices_per_process, use_cpu, pkg_root, resume,
                  attempt, extra_env):
    procs, logs = [], []
    for rank in range(num_processes):
        cfg = {"coordinator": coord, "num_processes": num_processes,
               "rank": rank, "data": str(data_path),
               "params": params, "num_boost_round": num_boost_round,
               "dataset_params": dict(dataset_params or {}),
               "out": out, "resume": resume or "",
               "env": {"JAX_PLATFORMS": "cpu"} if use_cpu else {}}
        cfg_path = os.path.join(td, f"cfg{rank}_a{attempt}.json")
        with open(cfg_path, "w") as fh:
            json.dump(cfg, fh)
        env = dict(os.environ)
        env.update(extra_env or {})
        env.pop("XLA_FLAGS", None)   # inherited flags never apply
        if devices_per_process > 0:
            env["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count="
                f"{devices_per_process}")
        if use_cpu:
            # the TPU site hook breaks multiprocess CPU backends;
            # keep only the package root on the path
            env["JAX_PLATFORMS"] = "cpu"
            env["PYTHONPATH"] = pkg_root
        else:
            # accelerator workers still need the package importable
            # when it is not pip-installed
            env["PYTHONPATH"] = os.pathsep.join(
                [pkg_root] + ([env["PYTHONPATH"]]
                              if env.get("PYTHONPATH") else []))
        # worker output goes to FILES: a filled 64KB stderr pipe
        # would stall that rank inside a collective and deadlock
        # the whole fleet until the timeout
        lf = open(os.path.join(td, f"rank{rank}_a{attempt}.log"), "w+b")
        logs.append(lf)
        procs.append(subprocess.Popen(
            [sys.executable, script, cfg_path], env=env,
            stdout=lf, stderr=subprocess.STDOUT))
    return procs, logs


def _kill_cohort(procs) -> None:
    for q in procs:
        if q.poll() is None:
            q.kill()
    for q in procs:
        q.wait()   # reap — no zombies in long-lived hosts


def _tail(logs, rank: int) -> str:
    try:
        logs[rank].seek(0)
        return logs[rank].read().decode(errors="replace")[-1500:]
    except Exception:
        return "<log unavailable>"


def train_distributed(params: Dict, data_path: str, num_processes: int,
                      num_boost_round: int = 100,
                      dataset_params: Optional[Dict] = None,
                      devices_per_process: int = 0,
                      coordinator_address: Optional[str] = None,
                      use_cpu: bool = True, timeout: float = 3600.0,
                      max_restarts: Optional[int] = None,
                      restart_backoff: Optional[float] = None,
                      fault_env: Optional[Dict[str, str]] = None):
    """Train ONE model with ``num_processes`` local worker processes over
    per-rank shards of ``data_path``; returns rank 0's Booster (every
    rank holds the identical model — tests/test_multiproc_train.py).

    ``devices_per_process`` > 0 forces that many virtual CPU devices per
    worker (XLA_FLAGS); ``use_cpu=False`` leaves the platform to the
    runtime (one accelerator process per host). The reference flow being
    mirrored: dask.py _train — partition per worker, port negotiation,
    per-worker local fit, rank-0 booster returned, others discarded.

    Fault tolerance: when ``params`` carry ``checkpoint_period`` (with
    ``checkpoint_dir`` defaulting to launcher scratch), a dead rank
    triggers cohort kill → newest all-rank-consistent checkpoint
    selection → respawn resuming from it, up to ``max_restarts`` times
    (default: the ``restart_max_retries`` param key, 2) with
    ``restart_backoff * 2^attempt`` seconds between attempts.
    ``fault_env`` injects chaos-test env vars (LIGHTGBM_TPU_FAULTS=...)
    into the workers; fired-fault markers persist across respawns so an
    injected crash fires exactly once.
    """
    from ..basic import Booster

    params = dict(params)
    params.setdefault("tree_learner", "data")
    if max_restarts is None:
        max_restarts = int(params.get("restart_max_retries", 2))
    if restart_backoff is None:
        restart_backoff = float(params.get("restart_backoff", 1.0))
    ckpt_period = int(params.get("checkpoint_period", 0) or 0)
    with tempfile.TemporaryDirectory(prefix="lgbm_tpu_launch_") as td:
        ckpt_dir = str(params.get("checkpoint_dir", "") or "")
        if ckpt_period > 0 and not ckpt_dir:
            ckpt_dir = os.path.join(td, "checkpoints")
            params["checkpoint_dir"] = ckpt_dir
        extra_env = dict(fault_env or {})
        # fired-fault markers shared across respawns: an injected crash
        # fires once per launcher call, not once per cohort attempt
        extra_env.setdefault(FAULT_STATE_ENV,
                             os.path.join(td, "fault_state"))
        script = os.path.join(td, "worker.py")
        with open(script, "w") as fh:
            fh.write(_WORKER)
        out = os.path.join(td, "model.txt")
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        deadline = time.time() + timeout
        attempt = 0
        resume = ""
        metrics_port = int(params.get("metrics_port", 0) or 0)
        if metrics_port > 0:
            # each rank's _setup_telemetry binds metrics_port + rank —
            # say where the endpoints are so the operator does not have
            # to derive the per-rank offsets from the docs
            log.info(
                "live OpenMetrics endpoints: %s (rank 0 also serves the "
                "fleet counter view)",
                ", ".join(f"http://127.0.0.1:{metrics_port + r}/metrics"
                          for r in range(num_processes)))
        while True:
            coord = coordinator_address or f"127.0.0.1:{_free_port()}"
            procs, logs = _spawn_cohort(
                td, script, params, data_path, num_processes,
                num_boost_round, dataset_params, out, coord,
                devices_per_process, use_cpu, pkg_root, resume, attempt,
                extra_env)
            failed_rank = None
            rc = None
            try:
                # poll, don't wait sequentially: the cohort must die
                # TOGETHER the moment one rank does — the survivors are
                # wedged inside a collective with a dead peer
                while True:
                    states = [q.poll() for q in procs]
                    bad = [(r, s) for r, s in enumerate(states)
                           if s is not None and s != 0]
                    if bad:
                        failed_rank, rc = bad[0]
                        break
                    if all(s == 0 for s in states):
                        break
                    if time.time() > deadline:
                        _kill_cohort(procs)
                        log.fatal("distributed training timed out after "
                                  "%.0fs (attempt %d)", timeout, attempt)
                    time.sleep(0.2)
            finally:
                if failed_rank is not None:
                    _kill_cohort(procs)
            if failed_rank is None:
                for lf in logs:
                    lf.close()
                break   # clean finish
            tail = _tail(logs, failed_rank)
            for lf in logs:
                lf.close()
            attempt += 1
            if attempt > max_restarts:
                log.fatal(
                    "distributed training failed after %d restart(s): "
                    "rank %d rc=%s: %s", max_restarts, failed_rank, rc,
                    tail)
            resume = (select_checkpoint(ckpt_dir, num_processes) or "") \
                if ckpt_dir else ""
            backoff = restart_backoff * (2 ** (attempt - 1))
            log.warning(
                "rank %d died (rc=%s); killed the cohort, restarting in "
                "%.1fs (attempt %d/%d) from %s\n%s", failed_rank, rc,
                backoff, attempt, max_restarts,
                resume or "scratch (no complete checkpoint)", tail[-400:])
            time.sleep(backoff)
        with open(out) as fh:
            return Booster(model_str=fh.read())
