"""Multi-process training launcher — the orchestration analog of the
reference's Dask integration.

The reference's ``dask.py`` finds open ports, builds the ``machines``
string, runs one local fit per worker, and returns rank 0's booster
(ref: python-package/lightgbm/dask.py:67-135 port negotiation, :166
``_train_part``, :392 ``_train``). On the JAX runtime the transport
negotiation collapses to ``jax.distributed.initialize`` against one
coordinator address; this module supplies the remaining orchestration:
spawn N processes, give each its rank, let each load its shard of the
data file (the loader reads per-rank row slices and allgathers the
binning sample), train ONE model jointly (``tree_learner=data`` over
the global mesh — parallel/multiproc.py), and hand back rank 0's
booster.

Single-host by default (N local processes, gloo collectives on CPU or
one process per accelerator); multi-host works by running the same
worker on every host with ``coordinator_address`` pointing at host 0 —
the exact shape of the reference's machine-list deployments.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
from typing import Dict, Optional

from ..utils import log

_WORKER = """
import json, os, sys
cfg = json.load(open(sys.argv[1]))
import jax
if cfg["env"].get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=cfg["coordinator"],
    num_processes=cfg["num_processes"], process_id=cfg["rank"])
import lightgbm_tpu as lgb

ds = lgb.Dataset(cfg["data"], params=cfg["dataset_params"])
bst = lgb.train(cfg["params"], ds,
                num_boost_round=cfg["num_boost_round"])
if jax.process_index() == 0:
    with open(cfg["out"], "w") as fh:
        fh.write(bst.model_to_string(num_iteration=-1))
"""


def _free_port() -> int:
    # NOTE: inherently racy (the socket closes before the coordinator
    # rebinds); SO_REUSEADDR narrows the window. Contended environments
    # should pass coordinator_address explicitly.
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def train_distributed(params: Dict, data_path: str, num_processes: int,
                      num_boost_round: int = 100,
                      dataset_params: Optional[Dict] = None,
                      devices_per_process: int = 0,
                      coordinator_address: Optional[str] = None,
                      use_cpu: bool = True, timeout: float = 3600.0):
    """Train ONE model with ``num_processes`` local worker processes over
    per-rank shards of ``data_path``; returns rank 0's Booster (every
    rank holds the identical model — tests/test_multiproc_train.py).

    ``devices_per_process`` > 0 forces that many virtual CPU devices per
    worker (XLA_FLAGS); ``use_cpu=False`` leaves the platform to the
    runtime (one accelerator process per host). The reference flow being
    mirrored: dask.py _train — partition per worker, port negotiation,
    per-worker local fit, rank-0 booster returned, others discarded.
    """
    from ..basic import Booster

    params = dict(params)
    params.setdefault("tree_learner", "data")
    coord = coordinator_address or f"127.0.0.1:{_free_port()}"
    with tempfile.TemporaryDirectory(prefix="lgbm_tpu_launch_") as td:
        script = os.path.join(td, "worker.py")
        with open(script, "w") as fh:
            fh.write(_WORKER)
        out = os.path.join(td, "model.txt")
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        procs = []
        logs = []
        for rank in range(num_processes):
            cfg = {"coordinator": coord, "num_processes": num_processes,
                   "rank": rank, "data": str(data_path),
                   "params": params, "num_boost_round": num_boost_round,
                   "dataset_params": dict(dataset_params or {}),
                   "out": out,
                   "env": {"JAX_PLATFORMS": "cpu"} if use_cpu else {}}
            cfg_path = os.path.join(td, f"cfg{rank}.json")
            with open(cfg_path, "w") as fh:
                json.dump(cfg, fh)
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)   # inherited flags never apply
            if devices_per_process > 0:
                env["XLA_FLAGS"] = (
                    "--xla_force_host_platform_device_count="
                    f"{devices_per_process}")
            if use_cpu:
                # the TPU site hook breaks multiprocess CPU backends;
                # keep only the package root on the path
                env["JAX_PLATFORMS"] = "cpu"
                env["PYTHONPATH"] = pkg_root
            else:
                # accelerator workers still need the package importable
                # when it is not pip-installed
                env["PYTHONPATH"] = os.pathsep.join(
                    [pkg_root] + ([env["PYTHONPATH"]]
                                  if env.get("PYTHONPATH") else []))
            # worker output goes to FILES: a filled 64KB stderr pipe
            # would stall that rank inside a collective and deadlock
            # the whole fleet until the timeout
            lf = open(os.path.join(td, f"rank{rank}.log"), "w+b")
            logs.append(lf)
            procs.append(subprocess.Popen(
                [sys.executable, script, cfg_path], env=env,
                stdout=lf, stderr=subprocess.STDOUT))
        errs = []
        for rank, p in enumerate(procs):
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                for q in procs:
                    q.wait()   # reap — no zombies in long-lived hosts
                log.fatal("distributed training timed out after %.0fs "
                          "(rank %d still running)", timeout, rank)
            if p.returncode != 0:
                logs[rank].seek(0)
                tail = logs[rank].read().decode(errors="replace")[-1500:]
                errs.append(f"rank {rank}: rc={p.returncode}: {tail}")
        for lf in logs:
            lf.close()
        if errs:
            log.fatal("distributed training failed:\n%s",
                      "\n".join(errs))
        with open(out) as fh:
            return Booster(model_str=fh.read())
