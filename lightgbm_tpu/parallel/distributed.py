"""Multi-host orchestration.

Replaces the reference's machine-list/port plumbing (ref: basic.py:2687
Booster.set_network -> LGBM_NetworkInit, dask.py:354
_machines_to_worker_map, src/network/linkers_socket.cpp all-pairs TCP
mesh) with JAX's process runtime: one `jax.distributed.initialize` call
per host, after which `jax.devices()` spans the pod slice and the SAME
mesh/shard_map training code (parallel/data_parallel.py,
parallel/tree_parallel.py) runs over ICI+DCN — no port negotiation, no
linker topology, no reduce-scatter schedules.

    # on every host (rank r of N):
    from lightgbm_tpu.parallel import distributed, make_mesh
    distributed.init_distributed("host0:1234", N, r)
    mesh = make_mesh()            # all pod devices
    ...train with make_sharded_grow_fn(mesh, ...)

`set_network` accepts the reference's machine-list parameters and maps
them onto initialize() so ported launch scripts keep working.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..utils import log

_INITIALIZED = False


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None
                     ) -> None:
    """Bring up the JAX process mesh (ref: the Network::Init role,
    network.h:89). Idempotent; TPU pod environments can usually omit all
    arguments (auto-detected from the TPU metadata)."""
    global _INITIALIZED
    if _INITIALIZED:
        log.warning("distributed runtime already initialized; ignoring")
        return
    import jax
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _INITIALIZED = True
    log.info("distributed runtime up: process %d/%d, %d local / %d global "
             "devices", jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def set_network(machines: str, local_listen_port: int = 12400,
                num_machines: int = 1, time_out: int = 120) -> None:
    """Reference-parameter shim (ref: basic.py:2687 set_network): the
    first machine acts as the coordinator; this host's rank is its
    position in the comma-separated list. ``time_out`` is accepted for
    signature compatibility (JAX handles connection retries itself)."""
    del time_out
    hosts = [m.strip() for m in machines.split(",") if m.strip()]
    if not hosts:
        raise ValueError("set_network: 'machines' must be a comma-separated "
                         "list of host[:port] entries, got an empty string")
    if num_machines > 1 and len(hosts) != num_machines:
        log.warning("machines lists %d hosts but num_machines=%d",
                    len(hosts), num_machines)
    import socket
    me = socket.gethostname()
    my_ids = {me}
    try:
        my_ids.add(socket.getfqdn())
        my_ids.add(socket.gethostbyname(me))
        my_ids.add("127.0.0.1")
        my_ids.add("localhost")
    except OSError:
        pass
    # rank: match host AND (when several entries share a host — multiple
    # processes per machine, as the reference format allows) this
    # process's local_listen_port
    candidates = [i for i, h in enumerate(hosts)
                  if h.split(":")[0] in my_ids]
    if not candidates:
        raise ValueError(
            f"set_network: none of the machines entries matches this host "
            f"({sorted(my_ids)}); list every worker's address, e.g. "
            f"'ip1:port,ip2:port'")
    if len(candidates) > 1:
        port_matches = [i for i in candidates
                        if ":" in hosts[i]
                        and hosts[i].rsplit(":", 1)[1].isdigit()
                        and int(hosts[i].rsplit(":", 1)[1])
                        == local_listen_port]
        if len(port_matches) != 1:
            raise ValueError(
                "set_network: multiple machines entries match this host; "
                "distinguish processes by giving each entry this "
                "process's local_listen_port")
        candidates = port_matches
    rank = candidates[0]
    # the coordinator is entry 0; its listed port wins over our local one
    c = hosts[0]
    if ":" in c and c.rsplit(":", 1)[1].isdigit():
        coord = f"{c.rsplit(':', 1)[0]}:{int(c.rsplit(':', 1)[1])}"
    else:
        coord = f"{c}:{local_listen_port}"
    init_distributed(coord, len(hosts), rank)


def free_network() -> None:
    """(ref: basic.py:2721 free_network) Shut down the process runtime."""
    global _INITIALIZED
    if not _INITIALIZED:
        return
    import jax
    jax.distributed.shutdown()
    _INITIALIZED = False
