"""Distributed tree learning over a jax.sharding.Mesh.

TPU-native replacement for the reference's network layer + parallel tree
learners (ref: src/network/*, src/treelearner/{data,feature,voting}_parallel
_tree_learner.cpp).  The socket/MPI collectives collapse into XLA collectives
over ICI/DCN (SURVEY.md §2.3):

- data-parallel:    rows sharded; histogram allreduce (`psum`) replaces the
                    reduce-scatter + SyncUpGlobalBestSplit exchange
                    (data_parallel_tree_learner.cpp:155-189,260).
- voting-parallel:  data-parallel + per-shard top-k feature voting caps the
                    allreduced payload (voting_parallel_tree_learner.cpp:151).
- feature-parallel: rows replicated, feature slices per shard; only 48-byte
                    best-split records are exchanged
                    (feature_parallel_tree_learner.cpp:60-77).
"""
from .mesh import make_mesh, replicate, shard_rows
from .data_parallel import make_sharded_grow_fn
from .tree_parallel import (make_feature_parallel_grow_fn,
                            make_voting_parallel_grow_fn)
from . import distributed
from .launcher import train_distributed

__all__ = [
    "make_mesh", "replicate", "shard_rows",
    "make_sharded_grow_fn",
    "make_feature_parallel_grow_fn", "make_voting_parallel_grow_fn",
    "distributed", "train_distributed", "collective_profile",
]


def collective_profile(mode: str, num_leaves: int, num_features: int,
                       max_bins: int, top_k: int = 20,
                       leafwise: bool = True):
    """(count, bytes) estimate of one tree's in-jit collective traffic
    for the telemetry registry — dispatches to the per-learner profiles
    (each documents the exchange it models next to the shard_map that
    performs it). Multi-process host-plane allgathers are counted for
    real by MultiProcLayout, not estimated here."""
    from . import data_parallel, tree_parallel
    if mode == "data":
        return data_parallel.collective_profile(num_leaves, num_features,
                                                max_bins, leafwise)
    if mode == "voting":
        return tree_parallel.voting_collective_profile(
            num_leaves, num_features, max_bins, top_k)
    if mode == "feature":
        return tree_parallel.feature_collective_profile(num_leaves)
    return 0, 0
