"""Binned dataset container + metadata.

TPU-native analog of the reference Dataset/DatasetLoader/Metadata
(ref: include/LightGBM/dataset.h:42,340, src/io/dataset_loader.cpp:203,
src/io/metadata.cpp).  Design deviation from the reference, on purpose:

- The reference stores per-feature-group ``Bin`` objects (dense uint8/16/32,
  4-bit packed, or delta-encoded sparse) and bundles exclusive sparse features
  (EFB) to cut CPU cache traffic.  On TPU the histogram kernel wants one dense
  ``[num_rows, num_features]`` integer matrix in HBM with static shape — dense
  uint8 at 255 bins is already the EFB-ideal layout for the MXU/VPU formulation,
  so feature bundling and sparse encodings are unnecessary; trivial features
  are simply dropped (same effect as the reference's pre-filter).
- Row-major layout matches the reference's multi-val (row-wise) path which it
  auto-selects for wide/fast cases (ref: src/io/dataset.cpp:591-680); the
  col-vs-row timing experiment collapses away because XLA tiles either way.
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN, BinMapper)
from .config import Config
from .utils import log


# the binning-defining keys a binary cache round-trips (the same family
# the C-API's UpdateParamChecking guards)
_DATASET_DEFINING_KEYS = (
    "max_bin", "max_bin_by_feature", "bin_construct_sample_cnt",
    "min_data_in_bin", "use_missing", "zero_as_missing",
    "feature_pre_filter", "min_data_in_leaf", "data_random_seed")


def dataset_defining_params(config: "Config") -> Dict[str, Any]:
    return {k: getattr(config, k) for k in _DATASET_DEFINING_KEYS}


class Metadata:
    """Label / weight / query-boundary / init-score holder
    (ref: include/LightGBM/dataset.h:42, src/io/metadata.cpp)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries+1]
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        log.check(label.size == self.num_data,
                  f"label size {label.size} != num_data {self.num_data}")
        self.label = label

    def set_weight(self, weight) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        log.check(weight.size == self.num_data,
                  f"weight size {weight.size} != num_data {self.num_data}")
        log.check(bool(np.all(weight >= 0)), "weights should be non-negative")
        self.weight = weight

    def set_group(self, group) -> None:
        """``group`` is per-query sizes (like the reference's query file);
        converted to cumulative boundaries (ref: metadata.cpp query_boundaries_)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        log.check(int(group.sum()) == self.num_data,
                  "sum of group sizes != num_data")
        self.query_boundaries = np.concatenate(
            [[0], np.cumsum(group)]).astype(np.int32)

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).reshape(-1)

    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


def _allgather_sample(sample: np.ndarray) -> np.ndarray:
    """Concatenate every process's binning sample (no-op single-process).

    process_allgather requires identical shapes on every rank, but row
    shards are unequal whenever the file row count doesn't divide evenly
    — gather the per-rank counts first, pad to the max, then slice each
    rank's real rows back out."""
    import jax
    if jax.process_count() <= 1:
        return sample
    from jax.experimental import multihost_utils
    n_proc = jax.process_count()
    cnt = np.array([sample.shape[0]], np.int64)
    cnts = np.asarray(multihost_utils.process_allgather(cnt)) \
        .reshape(n_proc)
    m = int(cnts.max())
    padded = np.pad(np.asarray(sample, np.float64),
                    ((0, m - sample.shape[0]), (0, 0)))
    gathered = np.asarray(multihost_utils.process_allgather(padded)) \
        .reshape(n_proc, m, sample.shape[1])
    return np.concatenate([gathered[p, :int(cnts[p])]
                           for p in range(n_proc)], axis=0)


def _sample_rows(num_data: int, sample_cnt: int, seed: int) -> np.ndarray:
    if num_data <= sample_cnt:
        return np.arange(num_data)
    rng = np.random.RandomState(seed)
    return np.sort(rng.choice(num_data, size=sample_cnt, replace=False))


def _encode_sparse_bundles(csc, mappers, used_features, layout,
                           most_freq_bins, n: int) -> np.ndarray:
    """[R, C] bundle-column matrix straight from CSC columns — the dense
    [R, F] logical matrix is never materialised. Bundle bin 0 = the row is
    default (most-frequent bin) in every member; conflicts keep the first
    member's encoding (ops/efb.py contract)."""
    C = layout.num_columns
    dtype = np.uint16 if max(layout.col_num_bin) > 255 else np.uint8
    out = np.zeros((n, C), dtype)
    for ci, bundle in enumerate(layout.bundles):
        col = np.zeros(n, np.int64)
        taken = np.zeros(n, bool)
        for k in bundle:
            j = used_features[k]
            m = mappers[j]
            off = int(layout.offset_of_feat[k])
            mfb = int(most_freq_bins[k])
            lo, hi = csc.indptr[j], csc.indptr[j + 1]
            rows_j = csc.indices[lo:hi]
            bins_nz = m.value_to_bin(
                np.asarray(csc.data[lo:hi], np.float64)).astype(np.int64)
            zero_bin = int(m.value_to_bin(np.zeros(1))[0])
            if zero_bin == mfb:
                # implicit zeros are default: only non-default nonzeros
                # need storing
                nd = bins_nz != mfb
                sel = rows_j[nd]
                keep = ~taken[sel]
                col[sel[keep]] = off + bins_nz[nd][keep]
                taken[sel[keep]] = True
            else:
                # zeros bin away from the most-frequent bin (e.g.
                # zero_as_missing): expand this member densely
                dense_bins = np.full(n, zero_bin, np.int64)
                dense_bins[rows_j] = bins_nz
                sel = np.nonzero((dense_bins != mfb) & ~taken)[0]
                col[sel] = off + dense_bins[sel]
                taken[sel] = True
        out[:, ci] = col.astype(dtype)
    return out


class TpuDataset:
    """The binned training matrix living in (or bound for) TPU HBM.

    ``bins``: ``[num_data, num_used_features]`` uint8/uint16; per-feature bin
    counts and offsets drive the joint histogram index.  ``mappers`` holds one
    BinMapper per *original* feature (trivial ones included, for model IO and
    prediction parity).
    """

    def __init__(self):
        self.bins: Optional[np.ndarray] = None
        self.mappers: List[BinMapper] = []
        self.used_features: List[int] = []   # original idx of non-trivial features
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.feature_names: List[str] = []
        self.metadata: Optional[Metadata] = None
        self.max_num_bin: int = 1
        # per used feature
        self.num_bin_per_feat: np.ndarray = np.zeros(0, np.int32)
        self.bin_offsets: np.ndarray = np.zeros(0, np.int32)
        self.most_freq_bins: np.ndarray = np.zeros(0, np.int32)
        self.is_categorical: np.ndarray = np.zeros(0, bool)
        self.raw_data: "np.ndarray" = None  # retained for linear trees
        self.missing_types: np.ndarray = np.zeros(0, np.int32)
        self.monotone_constraints: Optional[np.ndarray] = None
        # sparse-built datasets: ``bins`` holds EFB BUNDLE columns and
        # this carries the ops.efb.BundleLayout decode (None = logical)
        self.prebundled = None
        # streaming-ingest bookkeeping (ingest/): counters published into
        # the training telemetry registry at booster init, and the flag
        # that routes host->device transfer through the double-buffered
        # prefetcher (also set for mmap-backed cache loads)
        self.ingest_stats: Optional[Dict[str, Any]] = None
        self.streamed: bool = False
        # resolved dataset-defining params captured at mapper build —
        # persisted in the binary cache (the reference's .bin stores its
        # config too) so a reloaded dataset's booster resolves/echoes
        # the same values the original build used
        self.dataset_params: Dict[str, Any] = {}
        # True when the bins were produced against ANOTHER dataset's
        # mappers (validation builds): a cache of such a dataset must
        # never be reused as standalone training data
        self.reference_binned: bool = False

    # ------------------------------------------------------------------
    @classmethod
    def from_data(cls, data: np.ndarray, config: Config,
                  categorical_feature: Sequence[int] = (),
                  feature_names: Optional[List[str]] = None,
                  reference: Optional["TpuDataset"] = None,
                  forced_bounds: Optional[Dict[int, List[float]]] = None,
                  ) -> "TpuDataset":
        """Build from a dense float matrix.

        With ``reference`` set, reuse its bin mappers so validation data aligns
        with training bins (ref: dataset_loader.cpp:282
        LoadFromFileAlignWithOtherDataset).  Otherwise: sample rows, construct
        mappers per feature (ref: ConstructBinMappersFromTextData :988), then
        push binned values (ref: ExtractFeaturesFromMemory :1180).
        """
        from .utils.timer import global_timer as timer
        with timer.section("DatasetLoader::Construct"):
            return cls._from_data(data, config, categorical_feature,
                                  feature_names, reference, forced_bounds)

    @classmethod
    def _from_data(cls, data, config, categorical_feature=(),
                   feature_names=None, reference=None, forced_bounds=None):
        self = cls()
        data = np.asarray(data)
        if data.ndim != 2:
            log.fatal("data must be 2-dimensional")
        n, f = data.shape
        self.num_data = n
        self.num_total_features = f
        self.feature_names = (list(feature_names) if feature_names
                              else [f"Column_{i}" for i in range(f)])
        self.metadata = Metadata(n)

        if reference is not None:
            self.mappers = reference.mappers
            self.used_features = reference.used_features
            self.dataset_params = dict(
                getattr(reference, "dataset_params", {}) or {})
            self.reference_binned = True
            self._finalize_feature_arrays()
            self._push_data(data)
            return self

        cat_set = set(int(c) for c in categorical_feature)
        sample_idx = _sample_rows(n, config.bin_construct_sample_cnt,
                                  config.data_random_seed)
        sample = np.asarray(data[sample_idx], dtype=np.float64)
        self.build_mappers_from_sample(sample, config, cat_set,
                                       forced_bounds)
        self._push_data(data)
        if config.monotone_constraints:
            mc = np.asarray(config.monotone_constraints, dtype=np.int32)
            log.check(mc.size == f, "monotone_constraints length mismatch")
            self.monotone_constraints = mc
        return self

    def build_mappers_from_sample(self, sample: np.ndarray, config: Config,
                                  cat_set=frozenset(),
                                  forced_bounds=None) -> None:
        """Construct per-feature BinMappers from a float64 row sample and
        finalize the feature arrays.  The ONE mapper-construction path:
        the monolithic ``from_data`` and the chunked streaming ingest
        pipeline (ingest/pipeline.py, which collects the SAME sampled
        rows in bounded passes) both land here, so a streamed dataset's
        mappers are bit-identical to the monolithic build's by
        construction."""
        f = self.num_total_features
        self.dataset_params = dataset_defining_params(config)
        # distributed loading: every rank holds only its row shard — the
        # bin mappers must still be IDENTICAL everywhere, so the samples
        # are allgathered across processes before FindBin (the TPU-native
        # form of the reference's feature-sharded FindBin + mapper
        # allgather, ref: src/io/dataset_loader.cpp:1015,1146-1154)
        sample = _allgather_sample(sample)
        forced_bounds = forced_bounds or {}

        # per-feature bin budget override (ref: config.h
        # max_bin_by_feature, dataset_loader.cpp bin-mapper construction)
        mb_by_feat = list(config.max_bin_by_feature or [])
        if mb_by_feat and len(mb_by_feat) != f:
            log.fatal("max_bin_by_feature has %d entries but the data has "
                      "%d features" % (len(mb_by_feat), f))
        if any(int(b) <= 1 for b in mb_by_feat):
            log.fatal("max_bin_by_feature entries must be > 1")
        self.mappers = []
        for j in range(f):
            m = BinMapper()
            col = sample[:, j]
            bin_type = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
            # the reference feeds only the non-zero sampled values plus the
            # total count (zeros implicit); replicate that contract
            nz = col[(np.abs(col) > 1e-35) | np.isnan(col)]
            mb_j = int(mb_by_feat[j]) if mb_by_feat else config.max_bin
            m.find_bin(nz, total_sample_cnt=len(col), max_bin=mb_j,
                       min_data_in_bin=config.min_data_in_bin,
                       min_split_data=config.min_data_in_leaf if
                       config.feature_pre_filter else 0,
                       pre_filter=config.feature_pre_filter,
                       bin_type=bin_type, use_missing=config.use_missing,
                       zero_as_missing=config.zero_as_missing,
                       forced_bounds=forced_bounds.get(j))
            self.mappers.append(m)

        import jax as _jax
        if _jax.process_count() > 1:
            # retained (BINNED, 2 B/elem) for EFB: bundle layouts must be
            # IDENTICAL on every rank, so conflict masks come from this
            # shared sample (the reference also bundles from sampled
            # data, dataset_loader.cpp FindGroups over sample_indices)
            used = [j for j in range(f) if not self.mappers[j].is_trivial]
            if used:
                self.mp_sample_bins = np.stack(
                    [self.mappers[j].value_to_bin(sample[:, j])
                     for j in used], axis=1).astype(np.uint16)
        self.used_features = [j for j in range(f) if not self.mappers[j].is_trivial]
        if not self.used_features:
            # the reference keeps going and trains constant trees
            # (ref: src/io/dataset.cpp:336)
            log.warning("There are no meaningful features which satisfy "
                        "the provided configuration. Decrease Dataset "
                        "parameters min_data_in_bin or min_data_in_leaf "
                        "and re-construct Dataset might resolve this "
                        "warning.")
        self._finalize_feature_arrays()

    # ------------------------------------------------------------------
    @classmethod
    def from_sparse(cls, data, config: Config,
                    feature_names: Optional[List[str]] = None,
                    reference: Optional["TpuDataset"] = None,
                    ) -> "TpuDataset":
        """Build from a scipy CSR/CSC matrix WITHOUT materialising the
        dense [R, F] float matrix (ref: the reference's CSR/CSC dataset
        creation c_api.cpp:398-520 + sparse bin storage sparse_bin.hpp:73).

        The TPU-native storage answer differs from the reference's
        per-feature sparse bins: mutually-exclusive sparse features are
        bundled at INGESTION time (EFB, ref: dataset.cpp FindGroups/
        FastFeatureBundling) and only the [R, n_bundles] bundle-column
        matrix is ever materialised — histogram/scan work then scales
        with bundles, matching the role of the reference's MultiValBin.
        The resulting dataset is 'prebundled': ``bins`` holds BUNDLE
        columns and ``prebundled`` carries the decode layout.
        """
        import scipy.sparse as sp

        from .ops.efb import BundleLayout, find_bundles
        from .utils.timer import global_timer as timer
        with timer.section("DatasetLoader::ConstructSparse"):
            self = cls()
            csc = sp.csc_matrix(data)
            csc.sort_indices()
            n, f = csc.shape
            self.num_data = n
            self.num_total_features = f
            self.feature_names = (list(feature_names) if feature_names
                                  else [f"Column_{i}" for i in range(f)])
            self.metadata = Metadata(n)

            if reference is not None:
                # validation data is only ROUTED (never histogrammed), so
                # it stores EXACT logical bins: re-encoding through the
                # train bundles would silently drop conflicting values the
                # train sample never saw, skewing eval vs predict
                self.mappers = reference.mappers
                self.used_features = reference.used_features
                self.reference_binned = True
                self._finalize_feature_arrays()
                dtype = np.uint8 if self.max_num_bin <= 256 else np.uint16
                out = np.zeros((n, len(self.used_features)), dtype)
                for k, j in enumerate(self.used_features):
                    m = self.mappers[j]
                    lo, hi = csc.indptr[j], csc.indptr[j + 1]
                    zero_bin = int(m.value_to_bin(np.zeros(1))[0])
                    col = np.full(n, zero_bin, dtype)
                    col[csc.indices[lo:hi]] = m.value_to_bin(
                        np.asarray(csc.data[lo:hi], np.float64)) \
                        .astype(dtype)
                    out[:, k] = col
                self.bins = out
                return self

            # ---- sample + per-feature mappers (zeros implicit, like the
            # dense path / ref dataset_loader.cpp:988); one pass also
            # collects the sample non-default masks for bundling
            sample_idx = np.sort(_sample_rows(
                n, config.bin_construct_sample_cnt, config.data_random_seed))
            n_sample = len(sample_idx)
            self.mappers = []
            sample_masks = []

            def _in_sample(rows_j):
                # sorted-membership: O(nnz log n_sample) per column, no
                # per-call re-sorts (np.isin sorts its second arg)
                pos = np.searchsorted(sample_idx, rows_j)
                pos_c = np.minimum(pos, n_sample - 1)
                return (pos < n_sample) & (sample_idx[pos_c] == rows_j), \
                    pos_c

            for j in range(f):
                lo, hi = csc.indptr[j], csc.indptr[j + 1]
                rows_j = csc.indices[lo:hi]
                vals_j = csc.data[lo:hi]
                hit, pos = _in_sample(rows_j)
                nz = np.asarray(vals_j[hit], np.float64)
                nz = nz[(np.abs(nz) > 1e-35) | np.isnan(nz)]
                m = BinMapper()
                m.find_bin(nz, total_sample_cnt=n_sample,
                           max_bin=config.max_bin,
                           min_data_in_bin=config.min_data_in_bin,
                           min_split_data=(config.min_data_in_leaf
                                           if config.feature_pre_filter
                                           else 0),
                           pre_filter=config.feature_pre_filter,
                           bin_type=BIN_NUMERICAL,
                           use_missing=config.use_missing,
                           zero_as_missing=config.zero_as_missing)
                self.mappers.append(m)
                if not m.is_trivial:
                    mask = np.zeros(n_sample, bool)
                    mask[pos[hit]] = True
                    sample_masks.append(mask)
            self.used_features = [j for j in range(f)
                                  if not self.mappers[j].is_trivial]
            if not self.used_features:
                log.warning("There are no meaningful features which "
                            "satisfy the provided configuration.")
            self._finalize_feature_arrays()

            # ---- conflict-bounded bundling on the SAMPLE rows (the
            # reference also bundles from its sample,
            # dataset_loader.cpp FindGroups call sites)
            masks = sample_masks
            nb = [int(x) for x in self.num_bin_per_feat]
            bundles = find_bundles(
                masks, n_sample,
                max_conflict_rate=0.0,
                max_bundle_bins=int(config.tpu_max_bundle_bins),
                num_bin_per_feat=nb)
            layout = BundleLayout(bundles, nb)
            self.prebundled = layout
            self.bins = _encode_sparse_bundles(
                csc, self.mappers, self.used_features, layout,
                self.most_freq_bins, n)
            log.info("Sparse EFB: %d used features -> %d bundle columns "
                     "(max %d bins)", len(self.used_features),
                     layout.num_columns, max(layout.col_num_bin))
            if config.monotone_constraints:
                mc = np.asarray(config.monotone_constraints, dtype=np.int32)
                log.check(mc.size == f,
                          "monotone_constraints length mismatch")
                self.monotone_constraints = mc
            return self

    def _finalize_feature_arrays(self) -> None:
        from .binning import effective_bin_counts
        used = self.used_features
        self.num_bin_per_feat = effective_bin_counts(
            [self.mappers[j] for j in used])
        self.max_num_bin = int(self.num_bin_per_feat.max()) if used else 1
        self.bin_offsets = np.concatenate(
            [[0], np.cumsum(self.num_bin_per_feat)]).astype(np.int32)
        self.most_freq_bins = np.array(
            [self.mappers[j].most_freq_bin for j in used], np.int32)
        self.is_categorical = np.array(
            [self.mappers[j].bin_type == BIN_CATEGORICAL for j in used], bool)
        self.missing_types = np.array(
            [self.mappers[j].missing_type for j in used], np.int32)

    def bin_dtype(self):
        return np.uint8 if self.max_num_bin <= 256 else np.uint16

    def bin_rows(self, data: np.ndarray) -> np.ndarray:
        """Bin a [rows, num_total_features] float block against the
        finalized mappers -> packed [rows, num_used_features] uint8/16.
        The ONE binning hop for raw rows — the monolithic ``_push_data``
        and the chunked ingest pipeline both call it, so per-chunk
        binning is elementwise-identical to the whole-shard pass."""
        dtype = self.bin_dtype()
        # transpose copies on both sides keep every inner loop contiguous
        # (strided per-column access to the row-major matrices dominates
        # otherwise); float32 input stays float32 — value_to_bin bins it
        # exactly against pre-rounded f32 bounds
        dataT = np.ascontiguousarray(data.T)
        outT = np.empty((len(self.used_features), data.shape[0]),
                        dtype=dtype)
        for k, j in enumerate(self.used_features):
            outT[k] = self.mappers[j].value_to_bin(dataT[j]).astype(
                dtype, copy=False)
        return np.ascontiguousarray(outT.T)

    def _push_data(self, data: np.ndarray) -> None:
        self.bins = self.bin_rows(data)

    # ------------------------------------------------------------------
    def add_features_from(self, other: "TpuDataset") -> None:
        """Append the other dataset's features column-wise (ref:
        dataset.h AddFeaturesFrom / basic.py add_features_from). Both
        datasets must be constructed with the same row count; the other's
        mappers and binned columns are adopted as new features."""
        if other.num_data != self.num_data:
            log.fatal("add_features_from: row counts differ (%d vs %d)"
                      % (self.num_data, other.num_data))
        base = len(self.mappers)
        self.num_total_features += other.num_total_features
        self.mappers.extend(other.mappers)
        self.used_features.extend(base + j for j in other.used_features)
        self.feature_names = list(self.feature_names) + [
            f"{n}" if n not in self.feature_names else f"{n}_2"
            for n in other.feature_names]
        dtype = (np.uint16 if max(self.max_num_bin, other.max_num_bin) > 256
                 else self.bins.dtype)
        self.bins = np.concatenate(
            [np.asarray(self.bins, dtype), np.asarray(other.bins, dtype)],
            axis=1)
        if self.monotone_constraints is not None or                 other.monotone_constraints is not None:
            a = (self.monotone_constraints if self.monotone_constraints
                 is not None else np.zeros(base, np.int32))
            b = (other.monotone_constraints
                 if other.monotone_constraints is not None
                 else np.zeros(len(other.mappers), np.int32))
            self.monotone_constraints = np.concatenate([a, b])
        self._finalize_feature_arrays()

    @property
    def num_features(self) -> int:
        return len(self.used_features)

    def inner_feature_index(self, real_idx: int) -> int:
        """Original feature index -> used (inner) index, -1 if filtered
        (ref: dataset.h InnerFeatureIndex)."""
        try:
            return self.used_features.index(real_idx)
        except ValueError:
            return -1

    def real_feature_index(self, inner_idx: int) -> int:
        return self.used_features[inner_idx]

    def feature_infos(self) -> List[str]:
        """Per-original-feature info strings for the model text format
        (ref: gbdt_model_text.cpp feature_infos: ``[min:max]`` or categories)."""
        infos = []
        for m in self.mappers:
            if m.is_trivial:
                infos.append("none")
            elif m.bin_type == BIN_CATEGORICAL:
                cats = m.bin_2_categorical[1:]
                infos.append("[" + ":".join(str(c) for c in sorted(cats)) + "]")
            else:
                infos.append(f"[{m.min_val:g}:{m.max_val:g}]")
        return infos

    # ------------------------------------------------------------------
    def save_binary(self, path: str) -> None:
        """Binary dataset cache (analog of ref: dataset_loader.cpp:336
        LoadFromBinFile / Dataset::SaveBinaryFile).  Writes the sharded
        v2 artifact (ingest/cache.py): hash-manifested, versioned,
        written streaming + atomically, and mmap-able on reload so a
        cache-hit startup never re-parses text or re-bins."""
        from .ingest.cache import save_dataset_cache
        save_dataset_cache(self, path)

    @classmethod
    def load_binary(cls, path: str) -> "TpuDataset":
        """Load a binary dataset cache: the current v2 artifact
        (``LGBMTPU2``, mmap + manifest verification) or the legacy v1
        pickle (``LGBMTPU1``) written by earlier versions."""
        from .ingest.cache import CACHE_MAGIC, load_dataset_cache
        with open(path, "rb") as fh:
            magic = fh.read(8)
        if magic == CACHE_MAGIC:
            return load_dataset_cache(path)
        log.check(magic == b"LGBMTPU1", f"{path} is not a lightgbm_tpu "
                  "binary dataset file")
        with open(path, "rb") as fh:
            fh.read(8)
            payload = pickle.load(fh)
        self = cls()
        self.bins = payload["bins"]
        self.mappers = [BinMapper.from_dict(d) for d in payload["mappers"]]
        self.used_features = list(payload["used_features"])
        self.num_data = payload["num_data"]
        self.num_total_features = payload["num_total_features"]
        self.feature_names = payload["feature_names"]
        self.metadata = Metadata(self.num_data)
        if payload["label"] is not None:
            self.metadata.set_label(payload["label"])
        self.metadata.weight = payload["weight"]
        self.metadata.query_boundaries = payload["query_boundaries"]
        self.metadata.init_score = payload["init_score"]
        self.monotone_constraints = payload.get("monotone_constraints")
        self._finalize_feature_arrays()
        return self

    # ------------------------------------------------------------------
    def subset(self, row_indices: np.ndarray) -> "TpuDataset":
        """Row subset sharing mappers (ref: dataset.cpp CopySubrow — used by
        cv folds and bagging-subset paths)."""
        row_indices = np.asarray(row_indices)
        out = TpuDataset()
        out.bins = self.bins[row_indices]
        out.mappers = self.mappers
        out.used_features = self.used_features
        out.dataset_params = dict(self.dataset_params)
        out.num_data = len(row_indices)
        out.num_total_features = self.num_total_features
        out.feature_names = self.feature_names
        out.metadata = Metadata(out.num_data)
        md = self.metadata
        if md is not None:
            if md.label is not None:
                out.metadata.set_label(md.label[row_indices])
            if md.weight is not None:
                out.metadata.set_weight(md.weight[row_indices])
            if md.init_score is not None:
                init = md.init_score
                if init.size == self.num_data:
                    out.metadata.set_init_score(init[row_indices])
                else:
                    # flat [n*k] class-major init score: subset per class
                    k = init.size // self.num_data
                    sub = init.reshape(k, self.num_data)[:, row_indices]
                    out.metadata.set_init_score(sub.reshape(-1))
            if md.query_boundaries is not None:
                # rebuild query sizes over the kept rows (fold selections
                # keep whole queries; partial queries shrink consistently)
                # run-length encode query ids IN ROW ORDER so group sizes
                # stay aligned with the (possibly unsorted) subset rows
                qb = md.query_boundaries
                row_query = np.searchsorted(qb, row_indices, side="right") - 1
                if len(row_query):
                    change = np.concatenate(
                        [[True], row_query[1:] != row_query[:-1]])
                    starts = np.nonzero(change)[0]
                    seen = row_query[starts]
                    if len(np.unique(seen)) != len(seen):
                        log.warning(
                            "subset rows interleave query groups: a query's "
                            "rows are not contiguous in the subset, so it "
                            "is split into multiple groups — sort subset "
                            "indices by query to avoid this")
                    sizes = np.diff(np.concatenate([starts,
                                                    [len(row_query)]]))
                    out.metadata.set_group(sizes)
        out._finalize_feature_arrays()
        out.monotone_constraints = self.monotone_constraints
        return out
