"""Tree representation.

TPU-native analog of the reference flat-array tree (ref:
include/LightGBM/tree.h:25, src/io/tree.cpp).  Two forms:

- ``TreeArrays``: a NamedTuple of fixed-size device arrays (struct-of-arrays,
  static ``max_leaves`` slots) produced by the jitted learner.  Child pointers
  follow the reference convention: ``>= 0`` is an internal node index,
  negative is ``~leaf_index`` (ref: tree.h left_child_/right_child_).
- ``HostTree``: the host-side object used for model text IO, prediction on raw
  features, SHAP, and refit.  Thresholds are converted from bin indices to real
  values with the dataset's BinMapper upper bounds (ref: tree.h RealThreshold).
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class TreeArrays(NamedTuple):
    """Device-side tree under construction/training (static shapes)."""
    num_leaves: jax.Array          # int32 scalar — actual leaf count
    split_feature: jax.Array       # int32 [L-1] inner feature index
    threshold_bin: jax.Array       # int32 [L-1]
    default_left: jax.Array        # bool  [L-1]
    cat_flag: jax.Array            # bool  [L-1] categorical split?
    cat_mask: jax.Array            # bool  [L-1, B] bins routed left (cat only)
    left_child: jax.Array          # int32 [L-1]
    right_child: jax.Array         # int32 [L-1]
    split_gain: jax.Array          # f32   [L-1]
    internal_value: jax.Array      # f32   [L-1]
    internal_count: jax.Array      # f32   [L-1]
    internal_weight: jax.Array     # f32   [L-1] (sum_hessian)
    leaf_value: jax.Array          # f32   [L]
    leaf_count: jax.Array          # f32   [L]
    leaf_weight: jax.Array         # f32   [L] (sum_hessian)
    leaf_depth: jax.Array          # int32 [L]


def empty_tree(max_leaves: int, max_bins: int) -> TreeArrays:
    L = max_leaves
    return TreeArrays(
        num_leaves=jnp.int32(1),
        split_feature=jnp.full((L - 1,), -1, jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        default_left=jnp.zeros((L - 1,), bool),
        cat_flag=jnp.zeros((L - 1,), bool),
        cat_mask=jnp.zeros((L - 1, max_bins), bool),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        internal_value=jnp.zeros((L - 1,), jnp.float32),
        internal_count=jnp.zeros((L - 1,), jnp.float32),
        internal_weight=jnp.zeros((L - 1,), jnp.float32),
        leaf_value=jnp.zeros((L,), jnp.float32),
        leaf_count=jnp.zeros((L,), jnp.float32),
        leaf_weight=jnp.zeros((L,), jnp.float32),
        leaf_depth=jnp.zeros((L,), jnp.int32),
    )


class HostTree:
    """Host-side tree mirroring the reference text-model block
    (ref: src/io/tree.cpp:336 Tree::ToString)."""

    def __init__(self, num_leaves: int, shrinkage: float = 1.0):
        self.num_leaves = num_leaves
        self.shrinkage = shrinkage
        self.split_feature: np.ndarray = np.zeros(0, np.int32)   # real indices
        self.threshold: np.ndarray = np.zeros(0, np.float64)     # real values
        self.threshold_bin: np.ndarray = np.zeros(0, np.int32)
        self.decision_type: np.ndarray = np.zeros(0, np.int32)
        self.left_child: np.ndarray = np.zeros(0, np.int32)
        self.right_child: np.ndarray = np.zeros(0, np.int32)
        self.split_gain: np.ndarray = np.zeros(0, np.float64)
        self.internal_value: np.ndarray = np.zeros(0, np.float64)
        self.internal_weight: np.ndarray = np.zeros(0, np.float64)
        self.internal_count: np.ndarray = np.zeros(0, np.int64)
        self.leaf_value: np.ndarray = np.zeros(1, np.float64)
        self.leaf_weight: np.ndarray = np.zeros(1, np.float64)
        self.leaf_count: np.ndarray = np.zeros(1, np.int64)
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        self.leaf_depth: np.ndarray = np.zeros(1, np.int32)
        # linear trees (ref: tree.h is_linear_/leaf_const_/leaf_coeff_)
        self.is_linear = False
        self.leaf_const: np.ndarray = np.zeros(1, np.float64)
        self.leaf_features: List[List[int]] = []
        self.leaf_coeff: List[List[float]] = []

    # decision_type bitfield (ref: tree.h:166-186): bit0 categorical,
    # bit1 default_left, bits 2-3 missing type (0 none, 1 zero, 2 nan)
    @staticmethod
    def make_decision_type(categorical: bool, default_left: bool,
                           missing_type: int) -> int:
        d = 0
        if categorical:
            d |= 1
        if default_left:
            d |= 2
        d |= (missing_type & 3) << 2
        return d

    @staticmethod
    def decision_categorical(d: int) -> bool:
        return bool(d & 1)

    @staticmethod
    def decision_default_left(d: int) -> bool:
        return bool(d & 2)

    @staticmethod
    def decision_missing_type(d: int) -> int:
        return (d >> 2) & 3

    @property
    def num_internal(self) -> int:
        return max(0, self.num_leaves - 1)

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        """ref: tree.h:188 Shrinkage — scales leaf and internal values
        (and the linear models when present)."""
        self.shrinkage *= rate
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        if self.is_linear:
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [[c * rate for c in cs]
                               for cs in self.leaf_coeff]

    def add_bias(self, val: float) -> None:
        self.leaf_value = self.leaf_value + val
        self.internal_value = self.internal_value + val
        if self.is_linear:
            self.leaf_const = self.leaf_const + val
        self.shrinkage = 1.0

    # ------------------------------------------------------------------
    def predict_rows(self, X: np.ndarray) -> np.ndarray:
        """Vectorized node walk over raw features for a batch of rows
        (ref: tree.h Tree::Predict / Decision with missing routing)."""
        if self.is_linear:
            leaves = self.predict_leaf_index(X)
            return self._linear_outputs(X, leaves)
        n = X.shape[0]
        if self.num_leaves == 1:
            return np.full(n, self.leaf_value[0])
        out = np.empty(n, dtype=np.float64)
        # iterative vectorized traversal: node >= 0 internal, < 0 leaf (~leaf)
        node = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        for _ in range(self.num_leaves):  # depth bound
            if not active.any():
                break
            idx = np.nonzero(active)[0]
            nd = node[idx]
            f = self.split_feature[nd]
            vals = X[idx, f]
            d = self.decision_type[nd]
            cat = (d & 1).astype(bool)
            dl = (d & 2).astype(bool)
            mt = (d >> 2) & 3
            thr = self.threshold[nd]
            nan_mask = np.isnan(vals)
            zero_mask = np.abs(vals) <= 1e-35  # kZeroThreshold
            is_missing = np.where(mt == 2, nan_mask,
                                  np.where(mt == 1, zero_mask | nan_mask,
                                           False))
            # NaN with missing_type none/zero is converted to 0 by the
            # reference (tree.h NumericalDecision)
            vals_eff = np.where(nan_mask & (mt != 2), 0.0, vals)
            go_left = np.where(is_missing, dl, vals_eff <= thr)
            if cat.any():
                ci = np.nonzero(cat)[0]
                go_left[ci] = self._cat_decision(nd[ci], vals[ci])
            nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
            leaf_hit = nxt < 0
            if leaf_hit.any():
                out[idx[leaf_hit]] = self.leaf_value[~nxt[leaf_hit]]
            node[idx] = nxt
            active[idx] = ~leaf_hit
        return out

    def _cat_decision(self, nodes: np.ndarray, vals: np.ndarray) -> np.ndarray:
        """Categorical bitset lookup (ref: tree.h CategoricalDecision,
        Common::FindInBitset)."""
        go_left = np.zeros(len(nodes), dtype=bool)
        iv = np.where(np.isnan(vals), -1, vals).astype(np.int64)
        for k, (nd, v) in enumerate(zip(nodes, iv)):
            if v < 0:
                go_left[k] = False
                continue
            cat_idx = int(self.threshold[nd])  # index into cat_boundaries
            lo = self.cat_boundaries[cat_idx]
            hi = self.cat_boundaries[cat_idx + 1]
            word, bit = divmod(int(v), 32)
            if word < hi - lo and (self.cat_threshold[lo + word] >> bit) & 1:
                go_left[k] = True
        return go_left

    def _linear_outputs(self, X: np.ndarray,
                        leaves: np.ndarray) -> np.ndarray:
        """Per-leaf linear model outputs; rows with NaN in any leaf feature
        fall back to the constant leaf_value (ref: tree.cpp:130
        PredictLinear macro)."""
        out = np.empty(len(leaves), np.float64)
        for leaf in range(self.num_leaves):
            m = leaves == leaf
            if not m.any():
                continue
            feats = (self.leaf_features[leaf]
                     if leaf < len(self.leaf_features) else [])
            base = (self.leaf_const[leaf]
                    if leaf < len(self.leaf_const) else 0.0)
            if not feats:
                out[m] = base
                continue
            sub = X[np.ix_(m, feats)].astype(np.float64)
            nan_rows = np.isnan(sub).any(axis=1)
            coef = np.asarray(self.leaf_coeff[leaf], np.float64)
            vals = base + sub @ coef
            vals[nan_rows] = self.leaf_value[leaf]
            out[m] = vals
        return out

    def branch_features(self) -> List[List[int]]:
        """Per-leaf sorted unique feature sets along the root path
        (ref: tree.h branch_features_)."""
        paths: List[List[int]] = [[] for _ in range(self.num_leaves)]
        if self.num_internal == 0:
            return paths

        def walk(node, feats):
            feats = feats + [int(self.split_feature[node])]
            for child in (int(self.left_child[node]),
                          int(self.right_child[node])):
                if child < 0:
                    paths[~child] = sorted(set(feats))
                else:
                    walk(child, feats)
        walk(0, [])
        return paths

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        out = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)
        for _ in range(self.num_leaves):
            if not active.any():
                break
            idx = np.nonzero(active)[0]
            nd = node[idx]
            f = self.split_feature[nd]
            vals = X[idx, f]
            d = self.decision_type[nd]
            cat = (d & 1).astype(bool)
            dl = (d & 2).astype(bool)
            mt = (d >> 2) & 3
            thr = self.threshold[nd]
            nan_mask = np.isnan(vals)
            zero_mask = np.abs(vals) <= 1e-35
            is_missing = np.where(mt == 2, nan_mask,
                                  np.where(mt == 1, zero_mask | nan_mask, False))
            vals_eff = np.where(nan_mask & (mt != 2), 0.0, vals)
            go_left = np.where(is_missing, dl, vals_eff <= thr)
            if cat.any():
                ci = np.nonzero(cat)[0]
                go_left[ci] = self._cat_decision(nd[ci], vals[ci])
            nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
            leaf_hit = nxt < 0
            if leaf_hit.any():
                out[idx[leaf_hit]] = ~nxt[leaf_hit]
            node[idx] = nxt
            active[idx] = ~leaf_hit
        return out
