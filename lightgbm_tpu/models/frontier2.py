"""Frontier grower v2 — fused route+histogram level passes.

Round-2 replacement for models/frontier.py on the TPU path. One
``ops/fused_level.level_pass`` kernel invocation per tree level does the
routing AND the smaller-child histograms in a single streaming pass over
the binned matrix; everything else per level is small-tensor XLA glue:

- per-level slot counts are EXACT (1, 2, 4, ... capped at 128) instead of
  round 1's uniform 64 — histogram flops track the real frontier width;
- split finding runs on the 2*S new children only, updating a cached
  per-leaf best-split table, instead of rescanning all ``num_leaves``
  slots every level (ref: serial_tree_learner.cpp:379-453 only scans the
  two fresh leaves too);
- the [L, F, B] histogram pool is read/written with one-hot f32 matmuls:
  XLA per-row gathers/scatters measured ~8-14 ns/element on TPU, which
  would cost ~100 ms/tree at 255 leaves — the one-hot contraction is
  ~100 us of MXU time instead;
- after the capped-pow2 main levels, ``extra_levels`` additional passes
  (64 slots each) let skewed trees keep splitting until the leaf budget
  is spent — addressing the round-1 divergence from leaf-wise growth on
  skewed data (trees stopped near depth log2(num_leaves)+1).

Reference semantics preserved: smaller-child histogramming + sibling
subtraction (serial_tree_learner.cpp:283-323,423-425), leaf budget,
max_depth, missing routing, gain masks.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..ops.fused_level import (NCH_PRECISE, build_route_table,
                               build_route_table_bundled,
                               bundle_plane_views, expand_feature_mask,
                               hist_planes, level_pass, max_slot_cap,
                               pack_route_table, route_pass, table_lookup)
from ..ops.split import (BestSplit, SplitParams, best_split_cm,
                         calculate_leaf_output, per_feature_gains_cm)
from ..ops.collectives import record_psum
from .learner import (FeatureMeta, NEG_INF, _masked_gain, _masked_scatter,
                      merge_best_over_shards, meta_is_cat,
                      mono_child_bounds, mono_inter_level_update,
                      node_feature_mask, update_leaf_groups)
from .tree import TreeArrays, empty_tree


def level_caps(num_leaves: int, max_depth: int, extra_levels: int,
               slot_cap: int = 128):
    """Static per-level split caps: 1, 2, 4, ... (<= slot_cap) until the
    cumulative cap covers num_leaves-1, then ``extra_levels`` passes of
    min(64, slot_cap) more. The extras let skewed trees — and trees whose
    frontier outgrew slot_cap — spend the remaining leaf budget; levels
    with nothing to split are skipped at runtime (lax.cond), so extras
    cost compile time only."""
    caps = []
    cum = 0
    d = 0
    while cum < num_leaves - 1:
        if max_depth > 0 and d >= max_depth:
            break
        c = min(1 << d, slot_cap, num_leaves - 1)
        caps.append(c)
        cum += c
        d += 1
    caps.extend([min(64, slot_cap, num_leaves - 1)] * extra_levels)
    return tuple(caps)


def _onehot_dot(sel: jax.Array, mat: jax.Array) -> jax.Array:
    """sel @ mat with HIGHEST precision: sel is an exact 0/1 one-hot, so the
    f32-emulated TPU matmul reproduces the selected rows bit-for-bit (the
    default bf16-input MXU dot would round every pool histogram to ~8
    mantissa bits each level and wreck the sibling subtraction)."""
    return jax.lax.dot(sel, mat, precision=jax.lax.Precision.HIGHEST)


def _pool_read(pool_plane: jax.Array, leaf_of_slot: jax.Array,
               Sp: int) -> jax.Array:
    """pool[leaf_of_slot] as an exact one-hot f32 contraction."""
    L = pool_plane.shape[0]
    FB = pool_plane.shape[1] * pool_plane.shape[2]
    sel = (leaf_of_slot[:, None] ==
           jnp.arange(L, dtype=jnp.int32)[None, :]).astype(jnp.float32)
    out = _onehot_dot(sel, pool_plane.reshape(L, FB))
    return out.reshape((Sp,) + pool_plane.shape[1:])


def _pool_write(pool_plane: jax.Array, idx: jax.Array, vals: jax.Array,
                mask: jax.Array) -> jax.Array:
    """pool[idx[k]] = vals[k] where mask[k], as dense one-hot blend."""
    L = pool_plane.shape[0]
    F_oh, B = pool_plane.shape[1], pool_plane.shape[2]
    idx_safe = jnp.where(mask, idx, -1)
    sel = (idx_safe[:, None] ==
           jnp.arange(L, dtype=jnp.int32)[None, :]).astype(jnp.float32)
    upd = _onehot_dot(sel.T, vals.reshape(vals.shape[0], F_oh * B))  # [L,FB]
    hit = jnp.max(sel, axis=0)                                # [L] 0/1
    return (pool_plane * (1.0 - hit)[:, None, None]
            + upd.reshape(L, F_oh, B))


def _merge_best_many(best: BestSplit, idx: jax.Array, vals: BestSplit,
                     mask: jax.Array) -> BestSplit:
    return BestSplit(*[_masked_scatter(a, idx, v, mask)
                       for a, v in zip(best, vals)])


@functools.partial(
    jax.jit,
    static_argnames=("params", "num_leaves", "max_bins", "f_oh", "num_rows",
                     "nch", "max_depth", "extra_levels", "has_cat",
                     "use_mono_bounds", "use_node_masks", "interpret",
                     "bundle_cols", "bundle_col_bins", "psum_axis",
                     "defer_final_route", "mono_mode", "parallel_mode",
                     "top_k", "quant_bits", "packed", "mask_onehot"))
def grow_tree_fused(bins_T: jax.Array, gh_T: jax.Array, meta: FeatureMeta,
                    feature_mask: jax.Array, params: SplitParams,
                    num_leaves: int, max_bins: int, f_oh: int,
                    num_rows: int = 0, nch: int = NCH_PRECISE,
                    max_depth: int = -1, extra_levels: int = 3,
                    has_cat: bool = False, use_mono_bounds: bool = False,
                    use_node_masks: bool = False, node_masks=None,
                    bundle_cols: int = 0, bundle_col_bins: int = 0,
                    bundle_cfg=None, interpret: bool = False,
                    psum_axis: str = None, root_hist: jax.Array = None,
                    defer_final_route: bool = False,
                    mono_mode: str = "basic",
                    parallel_mode: str = "data", top_k: int = 0,
                    feature_shard_mask: jax.Array = None,
                    quant_bits: int = 0, packed=None,
                    mask_onehot: bool = False, gh_scales: jax.Array = None,
                    ):
    """Grow one tree with fused level passes.

    Args:
      bins_T: [Fp, Rp] int8/int16 transposed binned matrix; Rp a multiple
        of 2048 (the widest kernel tile — smaller pow2 multiples still
        work, the tile just shrinks to fit); padded feature rows
        all-zero; padded row COLUMNS can be
        anything (their gh is zero and their leaf starts at -1). With EFB
        (``bundle_cols > 0``) the rows are BUNDLE columns carrying
        ``bundle_col_bins`` bins each; splits/histograms stay logical.
      gh_T: [8, Rp] bfloat16 from ops.fused_level.pack_gh (zeros in padding
        columns).
      meta: FeatureMeta with arrays sized f_oh (padding features must carry
        num_bin=0 and feature_mask False).
      feature_mask: [f_oh] bool.
      num_rows: real row count R (0 = all Rp rows are real). Padding rows
        [R:] are pinned to leaf -1 so they never route, histogram, or
        receive score updates.
      bundle_cols/bundle_col_bins: kernel layout when the matrix holds EFB
        bundle columns (0 = unbundled); ``bundle_cfg`` is the
        models.learner.BundleCfg decode table plus meta.most-freq bins.
      psum_axis: when set (running under shard_map over a row-sharded
        mesh), every level histogram — ONE packed [FB, nch*Sp] f32 tensor
        per level — is allreduced over that mesh axis before the split
        scan, so all shards see GLOBAL sums and make identical split
        decisions; routing stays shard-local. This is the fused-engine
        analog of the reference's fast-path histogram reduction
        (ref: src/treelearner/data_parallel_tree_learner.cpp:185 — the
        GPU learner's histograms are what gets reduce-scattered, not a
        slow stand-in's). The hi/lo channel decode is linear, so psum
        before hist_planes preserves fp32-grade precision. Under
        psum_axis the caller passes ``num_rows=0`` and marks its local
        padding rows with zero gh weight instead (the global "real row"
        prefix has no meaning inside a shard).

      parallel_mode: composition with the distribution axis under
        psum_axis (ref: tree_learner.cpp:17-49 — the reference
        instantiates {Data,Voting,Feature}ParallelTreeLearner<GPU
        learner>; this is the fused engine's side of that matrix):
        - "data": full packed-histogram psum per level (round-2 path);
        - "voting": per-level top_k vote caps the exchanged columns —
          shards rank their local per-feature gains on the smaller-child
          planes, the 2*top_k global vote winners' [Sp, W, B, 3] planes
          are summed, everything else stays local-invalid; a per-leaf
          [L, f_oh] validity pool gates sibling subtraction and later
          scans (ref: voting_parallel_tree_learner.cpp:151-184). The
          root histogram is always a full exchange, like the XLA
          growers;
        - "feature": rows are REPLICATED on every shard (bins_T/gh_T in
          full), each shard scans only its feature_shard_mask columns
          and per-level best-split records are merged over the mesh
          (ref: feature_parallel_tree_learner.cpp:60-77
          SyncUpGlobalBestSplit). Zero histogram traffic; the histogram
          dot itself is NOT column-sliced in this engine (the fused
          kernel routes and histograms the same bins_T in one pass) —
          the XLA feature grower remains the compute-sliced path.
      top_k: voting-parallel vote width (2*top_k columns exchanged).
      feature_shard_mask: [f_oh] bool, this shard's owned columns
        (feature mode only).
      root_hist: optional precomputed root histogram [FB, nch*8] in the
        root-pass layout (slot 0 live) — produced by the previous
        iteration's fused boosting epilogue (ops/fused_level.epilogue_pass)
        so the root level_pass is skipped entirely.
      defer_final_route: when True, the statically-last level pass records
        its splits in the tree but does NOT route rows; the pass's route
        tables are returned for the epilogue kernel to apply. The returned
        row_leaf is then the PRE-final-route assignment.

    Returns (TreeArrays, row_leaf [Rp] int32 — caller slices to R; padding
    rows stay at -1). With defer_final_route:
    (tree, row_leaf, W_last, tbl_last).
    """
    Fp, Rp = bins_T.shape
    L = num_leaves
    B = max_bins
    use_bundles = bundle_cols > 0
    if use_bundles:
        k_foh, k_B = bundle_cols, bundle_col_bins   # kernel layout
    else:
        k_foh, k_B = f_oh, B
    # slot caps stay derived from the PADDED flat width so the level
    # schedule — and hence the grown tree — is invariant to the adaptive
    # packing (the adaptive-bin byte-identity A/B contract)
    caps = level_caps(L, max_depth, extra_levels,
                      slot_cap=max_slot_cap(k_foh * k_B, nch))
    kern_fb = packed.fb if packed is not None else k_foh * k_B

    def _decode(hist, Sp_):
        """Kernel accumulator -> (g, h, c) f32 planes on the logical
        padded layout: packed re-index (exact) + the quantized int32 ->
        f32 rescale boundary, both before any split search."""
        return hist_planes(hist, nch, Sp_, k_foh, k_B, packed=packed,
                           quant_bits=quant_bits, scales=gh_scales)

    if mask_onehot:
        # gain screening: masked features' one-hot slabs are zeroed in
        # the kernel. The leaf-totals column must survive: logical
        # feature 0 feeds the total sums (best_split_cm reads
        # grad[:, 0, :]) and the kernel's FIRST column carries the root
        # pass's every-row-left routing trick — keep both unmasked.
        keep0 = packed.feat_order[0] if packed is not None else 0
        fm_keep = feature_mask.at[0].set(True).at[keep0].set(True)
        fmask_fb = expand_feature_mask(fm_keep, k_foh, k_B, packed)
        fmask2d = jnp.broadcast_to(fmask_fb[:, None], (kern_fb, 128)) \
            .astype(jnp.int8 if quant_bits else jnp.bfloat16)
    else:
        fmask2d = None

    R = num_rows or Rp
    # padding rows sit at leaf -1; inactive slots use leaf_of_slot = -2 so
    # a -1 pad row never matches a slot
    leaf_T = jnp.where(jnp.arange(Rp)[None, :] < R, 0, -1).astype(jnp.int32)

    tree = empty_tree(L, B)
    pool_g = jnp.zeros((L, f_oh, B), jnp.float32)
    pool_h = jnp.zeros((L, f_oh, B), jnp.float32)
    pool_c = jnp.zeros((L, f_oh, B), jnp.float32)

    # ---------------- root pass: slot 0 collects the full-data histogram
    # (W0[0, bins of column 0] = 1 sends every row "left" on slot 0 —
    # each row's one-hot holds exactly one bin of column 0); skipped
    # entirely when the previous iteration's epilogue already built it
    Sp0 = 8
    if root_hist is not None:
        hist0 = root_hist
    else:
        # the root trick sends every row "left" over the FIRST kernel
        # column's one-hot — that column's width is the first packed
        # feature's slab under the adaptive layout
        w0_span = packed.widths[0] if packed is not None else k_B
        W0 = jnp.zeros((Sp0, kern_fb), jnp.bfloat16).at[0, :w0_span].set(1)
        tbl0 = jnp.zeros((Sp0, 128), jnp.int32)
        tbl0 = tbl0.at[:, 0].set(jnp.where(jnp.arange(Sp0) == 0, 0, -2))
        tbl0 = tbl0.at[0, 2].set(1)
        hist0, _ = level_pass(bins_T, leaf_T, gh_T, W0, tbl0, fmask2d,
                              num_slots=Sp0,
                              num_bins=k_B, f_oh=k_foh, nch=nch,
                              interpret=interpret, quant_bits=quant_bits,
                              packed=packed)
        # feature mode: rows are replicated, the local histogram IS the
        # global one (a psum would multiply by the shard count); voting:
        # the root is always a full exchange like the XLA growers
        if psum_axis is not None and parallel_mode != "feature":
            hist0 = record_psum(hist0, psum_axis)
    g0, h0, c0 = _decode(hist0, Sp0)
    if use_bundles:
        v = bundle_plane_views(jnp.stack([g0, h0, c0], axis=-1),
                               bundle_cfg.flat_idx, bundle_cfg.valid,
                               bundle_cfg.default_bin)
        g0, h0, c0 = v[..., 0], v[..., 1], v[..., 2]
    pool_g = pool_g.at[0].set(g0[0])
    pool_h = pool_h.at[0].set(h0[0])
    pool_c = pool_c.at[0].set(c0[0])
    root_g = jnp.sum(g0[0, 0, :])
    root_h = jnp.sum(h0[0, 0, :])
    root_c = jnp.sum(c0[0, 0, :])
    root_out = calculate_leaf_output(root_g, root_h, params, root_c, 0.0)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(root_out),
        leaf_count=tree.leaf_count.at[0].set(root_c),
        leaf_weight=tree.leaf_weight.at[0].set(root_h))

    leaf_lo = jnp.full((L,), -jnp.inf, jnp.float32)
    leaf_hi = jnp.full((L,), jnp.inf, jnp.float32)
    leaf_groups = jnp.full((L,), -1, jnp.int32)
    # intermediate monotone mode: per-leaf bin-space regions over the
    # LOGICAL features. Padded features (num_bin=0) get a fake [0, 1)
    # region so they always overlap — splits never touch them, and the
    # adjacency test needs overlap on every feature but one.
    reg_lo = jnp.zeros((L, f_oh), jnp.int32)
    reg_hi = jnp.broadcast_to(jnp.maximum(meta.num_bin, 1)[None, :],
                              (L, f_oh)).astype(jnp.int32)
    feat_par = psum_axis is not None and parallel_mode == "feature"
    root_mask = feature_mask[None, :]
    if feat_par:
        root_mask = root_mask & feature_shard_mask[None, :]
    if use_node_masks:
        root_mask = root_mask & node_feature_mask(
            node_masks, leaf_groups[:1], jnp.zeros((1,), jnp.int32))
    root_best = best_split_cm(
        g0[:1], h0[:1], c0[:1], meta.num_bin, meta.missing_type,
        meta.default_bin, root_mask, meta_is_cat(meta), meta.monotone,
        params, tree.leaf_value[:1], has_cat=has_cat,
        use_bounds=use_mono_bounds, bound_lo=leaf_lo[:1],
        bound_hi=leaf_hi[:1], leaf_depth=tree.leaf_depth[:1])
    if feat_par:
        # global winner over the column shards (the fused layout is
        # replicated, so local indices ARE global — offset 0)
        root_best = merge_best_over_shards(root_best, psum_axis, 0)
    best = BestSplit(*[jnp.zeros((L,) + a.shape[1:], a.dtype).at[0].set(a[0])
                       for a in root_best])
    best = best._replace(gain=best.gain.at[1:].set(NEG_INF))

    lpn = jnp.full((L,), -1, jnp.int32)   # leaf -> parent node
    lil = jnp.zeros((L,), bool)           # leaf is left child of its parent

    # deferred terminal-route tables. At most ONE route-only pass ever
    # fires per tree (the pass that exhausts the leaf budget, or the
    # statically-last pass): after it, no level can select splits again,
    # so its routing can safely ride the epilogue kernel instead. Tables
    # are padded to the widest level (an all-(-2) table routes nothing).
    Sp_max = max([8] + [max(8, c) for c in caps])
    def_W = jnp.zeros((Sp_max, kern_fb), jnp.bfloat16)
    def_tbl = jnp.zeros((Sp_max, 128), jnp.int32) \
        .at[:, 0].set(-2)

    # per-(leaf, feature) global-validity pool: under voting only the
    # vote winners' columns hold GLOBAL sums; sibling subtraction and
    # later scans must not touch local-only columns (the XLA leaf-wise
    # voting keeps the same plane)
    pool_valid = jnp.ones((L, f_oh), bool)
    state = (tree, leaf_T, pool_g, pool_h, pool_c, best, lpn, lil,
             leaf_lo, leaf_hi, leaf_groups, def_W, def_tbl,
             reg_lo, reg_hi, pool_valid)
    for li, S_d in enumerate(caps):
        state = _one_level(state, bins_T, gh_T, meta, feature_mask, params,
                           L, B, f_oh, S_d, nch, max_depth, has_cat,
                           use_mono_bounds, use_node_masks, node_masks,
                           li + 1, li == len(caps) - 1,
                           bundle_cols, bundle_col_bins, bundle_cfg,
                           interpret, psum_axis, defer_final_route,
                           mono_mode, parallel_mode, top_k,
                           feature_shard_mask,
                           quant_bits=quant_bits, packed=packed,
                           decode=_decode, fmask2d=fmask2d)
    tree, leaf_T = state[0], state[1]
    if defer_final_route:
        return tree, leaf_T[0], state[11], state[12]
    return tree, leaf_T[0]


def _one_level(state, bins_T, gh_T, meta, feature_mask, params, L, B, f_oh,
               S_d, nch, max_depth, has_cat, use_mono_bounds,
               use_node_masks, node_masks, fold, is_last,
               bundle_cols, bundle_col_bins, bundle_cfg, interpret,
               psum_axis=None, defer_final_route=False,
               mono_mode="basic", parallel_mode="data", top_k=0,
               feature_shard_mask=None, quant_bits=0, packed=None,
               decode=None, fmask2d=None):
    (tree, leaf_T, pool_g, pool_h, pool_c, best, lpn, lil,
     leaf_lo, leaf_hi, leaf_groups, def_W, def_tbl,
     reg_lo, reg_hi, pool_valid) = state
    use_bundles = bundle_cols > 0
    inter = use_mono_bounds and mono_mode == "intermediate"
    voting = psum_axis is not None and parallel_mode == "voting"
    # a vote covering every column is statically a full exchange: take
    # the data-parallel path verbatim (a gather+scatter round-trip would
    # leave XLA free to reduce in a different order — one-ULP drift for
    # zero saving)
    vote_live = voting and min(f_oh, 2 * top_k) < f_oh
    feat_par = psum_axis is not None and parallel_mode == "feature"
    Sp = max(8, S_d)
    slots = jnp.arange(L, dtype=jnp.int32)

    gains = _masked_gain(best, tree.leaf_depth, tree.num_leaves, max_depth, L)
    budget = L - tree.num_leaves
    order = jnp.argsort(-gains)
    rank = jnp.zeros((L,), jnp.int32).at[order].set(
        jnp.arange(L, dtype=jnp.int32))
    selected = (gains > 0.0) & (rank < budget) & (rank < S_d)
    n_sel = jnp.sum(selected.astype(jnp.int32))

    def do_level(op):
        return _apply_level(op, False)

    def do_level_route(op):
        # this pass's histograms can never be consumed (no split search
        # will ever run again): route rows + record the splits, skip the
        # histogram dot / pool updates / child scans (~60% of the cost of
        # a deep pass)
        return _apply_level(op, True)

    def _apply_level(op, route_only):
        (tree, leaf_T, pool_g, pool_h, pool_c, best, lpn, lil,
         leaf_lo, leaf_hi, leaf_groups, def_W, def_tbl,
         reg_lo, reg_hi, pool_valid) = op
        sel_i32 = selected.astype(jnp.int32)
        k_of_leaf = jnp.cumsum(sel_i32) - sel_i32
        new_of_leaf = jnp.where(selected, tree.num_leaves + k_of_leaf, -1)
        # node index base: a tree with N leaves has N-1 internal nodes
        node_of_leaf = jnp.where(selected,
                                 tree.num_leaves - 1 + k_of_leaf, -1)

        # ---- slot tables (leaf_of_slot = -2 marks inactive slots so they
        # can never match the -1 of padding rows)
        lof = _masked_scatter(
            jnp.full((Sp,), -2, jnp.int32),
            jnp.minimum(k_of_leaf, Sp - 1), slots,
            selected & (k_of_leaf < Sp))
        lof_on = lof >= 0
        lof_safe = jnp.maximum(lof, 0)
        feat_s = jnp.where(lof_on, best.feature[lof_safe], -1)
        thr_s = best.threshold[lof_safe]
        dl_s = best.default_left[lof_safe]
        cf_s = best.cat_flag[lof_safe] & lof_on
        cm_s = best.cat_mask[lof_safe]
        small_left_s = (best.left_count[lof_safe]
                        <= best.right_count[lof_safe])
        new_s = jnp.where(lof_on, tree.num_leaves + jnp.arange(Sp), 0)
        delta_s = jnp.where(lof_on, new_s - lof_safe, 0)

        if use_bundles:
            W = build_route_table_bundled(
                feat_s, thr_s, dl_s, meta.num_bin, meta.missing_type,
                meta.default_bin, bundle_cfg.default_bin,
                bundle_cfg.col_of_feat, bundle_cfg.offset_of_feat,
                bundle_cols, bundle_col_bins,
                cat_flag=cf_s if has_cat else None,
                cat_mask=cm_s if has_cat else None)
        else:
            W = build_route_table(feat_s, thr_s, dl_s, meta.num_bin,
                                  meta.missing_type, meta.default_bin,
                                  Sp, f_oh, B,
                                  cat_flag=cf_s if has_cat else None,
                                  cat_mask=cm_s if has_cat else None)
            if packed is not None:
                # route tables are built on the logical padded layout and
                # re-indexed onto the packed flat axis (exact 0/1 gather)
                W = pack_route_table(W, packed)
        tbl = jnp.zeros((Sp, 128), jnp.int32)
        tbl = tbl.at[:, 0].set(lof)
        tbl = tbl.at[:, 1].set(delta_s)
        tbl = tbl.at[:, 2].set(small_left_s.astype(jnp.int32))

        k_foh = bundle_cols if use_bundles else f_oh
        k_B = bundle_col_bins if use_bundles else B
        # ---- THE level pass: route (+ smaller-child histograms)
        def_W2, def_tbl2 = def_W, def_tbl
        if route_only and defer_final_route:
            # the epilogue kernel applies this pass's routing; hand it the
            # (width-padded) tables and keep leaf_T at the pre-terminal
            # assignment. Only one route-only pass can ever fire, so the
            # single write is never clobbered.
            leaf_T2 = leaf_T
            def_W2 = jnp.zeros_like(def_W).at[:Sp].set(W)
            def_tbl2 = jnp.zeros_like(def_tbl).at[:, 0].set(-2) \
                .at[:Sp].set(tbl)
            pool_g2, pool_h2, pool_c2 = pool_g, pool_h, pool_c
            pool_valid2 = pool_valid
        elif route_only:
            leaf_T2 = route_pass(bins_T, leaf_T, W, tbl, num_slots=Sp,
                                 num_bins=k_B, f_oh=k_foh,
                                 interpret=interpret, packed=packed)
            pool_g2, pool_h2, pool_c2 = pool_g, pool_h, pool_c
            pool_valid2 = pool_valid
        else:
            hist, leaf_T2 = level_pass(
                bins_T, leaf_T, gh_T, W, tbl, fmask2d, num_slots=Sp,
                num_bins=k_B, f_oh=k_foh, nch=nch, interpret=interpret,
                quant_bits=quant_bits, packed=packed)
            if psum_axis is not None and not vote_live and not feat_par:
                hist = record_psum(hist, psum_axis)

            # ---- voting exchange: rank local per-feature gains on the
            # smaller-child planes, psum the votes, and sum only the
            # top-W winners' columns over the mesh; everything else is
            # zeroed and marked invalid for later scans
            # (ref: voting_parallel_tree_learner.cpp:151-184; same vote
            # rule as the XLA growers' _exchange)
            if vote_live:
                # local decode just for the vote ranking
                lg, lh, lc = decode(hist, Sp)
                if use_bundles:
                    v = bundle_plane_views(
                        jnp.stack([lg, lh, lc], axis=-1),
                        bundle_cfg.flat_idx, bundle_cfg.valid,
                        bundle_cfg.default_bin)
                    lg, lh, lc = v[..., 0], v[..., 1], v[..., 2]
                # the smaller child's own post-split output is its
                # path-smoothing parent (matches the child-scan call)
                sm_out = jnp.where(
                    small_left_s,
                    jnp.where(lof_on, best.left_output[lof_safe], 0.0),
                    jnp.where(lof_on, best.right_output[lof_safe], 0.0))
                vote_mask = jnp.broadcast_to(feature_mask[None, :],
                                             (Sp, f_oh)) & lof_on[:, None]
                gains_loc = per_feature_gains_cm(
                    lg, lh, lc, meta.num_bin, meta.missing_type,
                    meta.default_bin, vote_mask, meta_is_cat(meta),
                    meta.monotone, params, sm_out, has_cat=has_cat)
                k_v = min(top_k, f_oh)
                W_vote = min(f_oh, 2 * top_k)
                kth = jnp.sort(gains_loc, axis=1)[:, f_oh - k_v][:, None]
                votes = (gains_loc >= kth) & jnp.isfinite(gains_loc)
                votes = record_psum(votes.astype(jnp.int32), psum_axis)
                score_f = jnp.sum(votes, axis=0)
                _, w_idx = jax.lax.top_k(score_f, W_vote)
                lvl_valid = jnp.zeros((f_oh,), bool).at[w_idx].set(True)
                if use_bundles:
                    # logical features interleave inside bundle columns;
                    # exchange the DECODED logical planes (divergence vs
                    # the unbundled path: decode-then-psum rounds
                    # differently than psum-then-decode — documented,
                    # bundles+voting only)
                    stack = jnp.stack([lg, lh, lc], axis=-1)
                    sub = record_psum(jnp.take(stack, w_idx, axis=1),
                                       psum_axis)
                    stack = jnp.zeros_like(stack).at[:, w_idx].set(sub)
                    sm_g, sm_h, sm_c = (stack[..., 0], stack[..., 1],
                                        stack[..., 2])
                else:
                    # exchange the PACKED hi/lo channels of the winning
                    # columns so the decode happens AFTER the global sum
                    # — bit-identical to the data-parallel path when
                    # every column wins (top_k >= F)
                    hr = hist.reshape(k_foh, k_B, -1)
                    sub = record_psum(jnp.take(hr, w_idx, axis=0),
                                       psum_axis)
                    hr = jnp.zeros_like(hr).at[w_idx].set(sub)
                    hist = hr.reshape(k_foh * k_B, -1)
                    sm_g, sm_h, sm_c = decode(hist, Sp)
            else:
                lvl_valid = jnp.ones((f_oh,), bool)
                sm_g, sm_h, sm_c = decode(hist, Sp)
                if use_bundles:
                    v = bundle_plane_views(
                        jnp.stack([sm_g, sm_h, sm_c], axis=-1),
                        bundle_cfg.flat_idx, bundle_cfg.valid,
                        bundle_cfg.default_bin)
                    sm_g, sm_h, sm_c = v[..., 0], v[..., 1], v[..., 2]

            # ---- sibling by subtraction from the parent pool
            par_g = _pool_read(pool_g, lof_safe, Sp)
            par_h = _pool_read(pool_h, lof_safe, Sp)
            par_c = _pool_read(pool_c, lof_safe, Sp)
            sb_g, sb_h, sb_c = par_g - sm_g, par_h - sm_h, par_c - sm_c
            sl = small_left_s[:, None, None]
            left_g = jnp.where(sl, sm_g, sb_g)
            left_h = jnp.where(sl, sm_h, sb_h)
            left_c = jnp.where(sl, sm_c, sb_c)
            right_g = jnp.where(sl, sb_g, sm_g)
            right_h = jnp.where(sl, sb_h, sm_h)
            right_c = jnp.where(sl, sb_c, sm_c)

            pool_g2 = _pool_write(pool_g, lof_safe, left_g, lof_on)
            pool_g2 = _pool_write(pool_g2, new_s, right_g, lof_on)
            pool_h2 = _pool_write(pool_h, lof_safe, left_h, lof_on)
            pool_h2 = _pool_write(pool_h2, new_s, right_h, lof_on)
            pool_c2 = _pool_write(pool_c, lof_safe, left_c, lof_on)
            pool_c2 = _pool_write(pool_c2, new_s, right_c, lof_on)
            # validity: the exchanged (smaller) side is valid where the
            # vote summed it; the subtracted side additionally needs a
            # globally-valid parent (root is fully valid, so data/
            # feature modes stay all-true)
            if vote_live:
                par_v = pool_valid[lof_safe]          # [Sp, f_oh]
                sm_v = jnp.broadcast_to(lvl_valid[None, :], (Sp, f_oh))
                sb_v = par_v & sm_v
                sl2 = small_left_s[:, None]
                left_v = jnp.where(sl2, sm_v, sb_v)
                right_v = jnp.where(sl2, sb_v, sm_v)
                pool_valid2 = _masked_scatter(pool_valid, lof_safe,
                                              left_v, lof_on)
                pool_valid2 = _masked_scatter(pool_valid2, new_s,
                                              right_v, lof_on)
            else:
                pool_valid2 = pool_valid

        # ---- tree bookkeeping (ref: tree.h:62 Tree::Split; same node
        # array conventions as models/frontier.py round 1)
        f_l = best.feature
        new_depth = tree.leaf_depth + 1

        def w(arr, vals):
            return _masked_scatter(arr, node_of_leaf, vals, selected)
        sf = w(tree.split_feature, f_l)
        tb = w(tree.threshold_bin, best.threshold)
        dfl = w(tree.default_left, best.default_left)
        cfw = w(tree.cat_flag, best.cat_flag)
        cmw = w(tree.cat_mask, best.cat_mask)
        sg = w(tree.split_gain, best.gain)
        iv = w(tree.internal_value, tree.leaf_value)
        ic = w(tree.internal_count, tree.leaf_count)
        iw = w(tree.internal_weight, tree.leaf_weight)
        lc = w(tree.left_child, -slots - 1)
        rc = w(tree.right_child, -new_of_leaf - 1)
        wl = selected & (lpn >= 0) & lil
        wr = selected & (lpn >= 0) & ~lil
        lc = _masked_scatter(lc, lpn, node_of_leaf, wl)
        rc = _masked_scatter(rc, lpn, node_of_leaf, wr)
        lpn2 = jnp.where(selected, node_of_leaf, lpn)
        lil2 = jnp.where(selected, True, lil)
        lpn2 = _masked_scatter(lpn2, new_of_leaf, node_of_leaf, selected)
        lil2 = _masked_scatter(lil2, new_of_leaf, jnp.zeros((L,), bool),
                               selected)

        def upd2(arr, lv, rv):
            arr = _masked_scatter(arr, slots, lv, selected)
            return _masked_scatter(arr, new_of_leaf, rv, selected)
        if inter:
            # intermediate monotone: sequential per-split clipping/fences
            # over [L]-state (models/learner.mono_inter_level_update);
            # clipped child outputs replace the raw scan outputs
            (lv_inter, leaf_lo2, leaf_hi2, reg_lo2, reg_hi2,
             mono_changed) = mono_inter_level_update(
                tree.leaf_value, leaf_lo, leaf_hi, reg_lo, reg_hi,
                selected, k_of_leaf, best.feature, best.threshold,
                best.cat_flag, best.left_output, best.right_output,
                meta.monotone, tree.num_leaves, Sp)
            new_leaf_value = lv_inter
        else:
            new_leaf_value = upd2(tree.leaf_value, best.left_output,
                                  best.right_output)
            reg_lo2, reg_hi2 = reg_lo, reg_hi
            mono_changed = None
        tree2 = tree._replace(
            num_leaves=tree.num_leaves + n_sel,
            split_feature=sf, threshold_bin=tb, default_left=dfl,
            cat_flag=cfw, cat_mask=cmw,
            split_gain=sg, internal_value=iv, internal_count=ic,
            internal_weight=iw, left_child=lc, right_child=rc,
            leaf_value=new_leaf_value,
            leaf_count=upd2(tree.leaf_count, best.left_count,
                            best.right_count),
            leaf_weight=upd2(tree.leaf_weight, best.left_sum_hess,
                             best.right_sum_hess),
            leaf_depth=upd2(tree.leaf_depth, new_depth, new_depth),
        )

        # ---- bound/group propagation (cheap [L]-sized state upkeep,
        # shared by both variants)
        if use_mono_bounds and not inter:
            mono_dir = jnp.where(best.feature >= 0,
                                 meta.monotone[jnp.maximum(best.feature, 0)],
                                 0)
            # reference gates constraint updates on is_numerical_split
            mono_dir = jnp.where(best.cat_flag, 0, mono_dir)
            leaf_lo2, leaf_hi2 = mono_child_bounds(
                leaf_lo, leaf_hi, leaf_lo, leaf_hi, selected, mono_dir,
                best.left_output, best.right_output,
                jnp.arange(L, dtype=jnp.int32), new_of_leaf)
        elif not use_mono_bounds:
            leaf_lo2, leaf_hi2 = leaf_lo, leaf_hi
        if use_node_masks:
            leaf_groups2 = update_leaf_groups(
                node_masks, leaf_groups, best.feature, selected,
                jnp.arange(L, dtype=jnp.int32), new_of_leaf)
        else:
            leaf_groups2 = leaf_groups

        if route_only:
            # no split search will ever run again; just bar the fresh
            # leaves (and the reused parent slots) from re-selection
            neg = jnp.full((L,), NEG_INF, jnp.float32)
            g2 = _masked_scatter(best.gain, slots, neg, selected)
            g2 = _masked_scatter(g2, new_of_leaf, neg, selected)
            best2 = best._replace(gain=g2)
            return (tree2, leaf_T2, pool_g2, pool_h2, pool_c2, best2,
                    lpn2, lil2, leaf_lo2, leaf_hi2, leaf_groups2,
                    def_W2, def_tbl2, reg_lo2, reg_hi2, pool_valid2)

        # ---- best splits for the 2*Sp fresh children only; each child's
        # own post-split output is the parent_output for path smoothing of
        # its prospective grandchildren (matches learner.py:208 and ref
        # feature_histogram.hpp FindBestThreshold parent_output usage).
        # Intermediate mode reads the CLIPPED outputs from the tree.
        if inter:
            left_out = jnp.where(lof_on, tree2.leaf_value[lof_safe], 0.0)
            right_out = jnp.where(lof_on, tree2.leaf_value[new_s], 0.0)
        else:
            left_out = jnp.where(lof_on, best.left_output[lof_safe], 0.0)
            right_out = jnp.where(lof_on, best.right_output[lof_safe], 0.0)
        ch_g = jnp.concatenate([left_g, right_g], axis=0)
        ch_h = jnp.concatenate([left_h, right_h], axis=0)
        ch_c = jnp.concatenate([left_c, right_c], axis=0)
        if use_mono_bounds:
            ch_lo = jnp.concatenate([leaf_lo2[lof_safe], leaf_lo2[new_s]])
            ch_hi = jnp.concatenate([leaf_hi2[lof_safe], leaf_hi2[new_s]])
        else:
            ch_lo = ch_hi = None
        ch_mask = feature_mask[None, :]
        if vote_live:
            # scans must not read local-only (unexchanged) columns
            ch_mask = ch_mask & jnp.concatenate([left_v, right_v], axis=0)
        if feat_par:
            ch_mask = ch_mask & feature_shard_mask[None, :]
        if use_node_masks:
            ch_groups = jnp.concatenate([leaf_groups2[lof_safe],
                                         leaf_groups2[new_s]])
            # per-node sampling identity: creating node id + side bit
            ch_ids = jnp.concatenate([2 * (node_of_leaf[lof_safe] + 1) + 1,
                                      2 * (node_of_leaf[lof_safe] + 1)])
            ch_mask = ch_mask & node_feature_mask(node_masks, ch_groups,
                                                  ch_ids)
        ch_depth = jnp.concatenate([tree2.leaf_depth[lof_safe],
                                    tree2.leaf_depth[new_s]])
        bs = best_split_cm(
            ch_g, ch_h, ch_c, meta.num_bin, meta.missing_type,
            meta.default_bin, ch_mask, meta_is_cat(meta), meta.monotone,
            params, jnp.concatenate([left_out, right_out]),
            has_cat=has_cat, use_bounds=use_mono_bounds, bound_lo=ch_lo,
            bound_hi=ch_hi, leaf_depth=ch_depth)
        if feat_par:
            # per-level SyncUpGlobalBestSplit over the column shards
            # (ref: parallel_tree_learner.h:191); offset 0 — the fused
            # layout is replicated, local indices are global
            bs = merge_best_over_shards(bs, psum_axis, 0)
        left_bs = BestSplit(*[a[:Sp] for a in bs])
        right_bs = BestSplit(*[a[Sp:] for a in bs])
        best2 = _merge_best_many(best, lof_safe, left_bs, lof_on)
        best2 = _merge_best_many(best2, new_s, right_bs, lof_on)

        if inter:
            # stale-leaf recompute: pre-existing leaves whose bounds the
            # cross-tightening touched re-derive their cached best split
            # from the pool with the new bounds (ref:
            # serial_tree_learner.cpp:706-714 recompute of leaves_to_update)
            def _rescan(b):
                node_ids = 2 * (lpn2 + 1) + lil2.astype(jnp.int32)
                m = feature_mask[None, :]
                if use_node_masks:
                    m = m & node_feature_mask(node_masks, leaf_groups2,
                                              node_ids)
                bs_all = best_split_cm(
                    pool_g2, pool_h2, pool_c2, meta.num_bin,
                    meta.missing_type, meta.default_bin,
                    jnp.broadcast_to(m, (L, f_oh)) & pool_valid2,
                    meta_is_cat(meta),
                    meta.monotone, params, tree2.leaf_value,
                    has_cat=has_cat, use_bounds=True, bound_lo=leaf_lo2,
                    bound_hi=leaf_hi2, leaf_depth=tree2.leaf_depth)

                def merge(old, newv):
                    mm = (mono_changed if old.ndim == 1
                          else mono_changed[:, None])
                    return jnp.where(mm, newv, old)
                return BestSplit(*[merge(o, n) for o, n in zip(b, bs_all)])

            best2 = jax.lax.cond(jnp.any(mono_changed), _rescan,
                                 lambda b: b, best2)

        return (tree2, leaf_T2, pool_g2, pool_h2, pool_c2, best2, lpn2,
                lil2, leaf_lo2, leaf_hi2, leaf_groups2, def_W2, def_tbl2,
                reg_lo2, reg_hi2, pool_valid2)

    op0 = (tree, leaf_T, pool_g, pool_h, pool_c, best, lpn, lil,
           leaf_lo, leaf_hi, leaf_groups, def_W, def_tbl, reg_lo, reg_hi,
           pool_valid)

    def dispatch(op):
        if is_last:
            # final scheduled pass: its histograms are never consumed
            return do_level_route(op)
        # dynamic: once the leaf budget will be exhausted by this level's
        # splits, no later split search can select anything
        budget_after = budget - n_sel
        return jax.lax.cond(budget_after > 0, do_level, do_level_route, op)

    return jax.lax.cond(n_sel > 0, dispatch, lambda op: op, op0)


def tree_score_delta(tree: TreeArrays, row_leaf: jax.Array, shrinkage,
                     num_rows: int = 0,
                     interpret: bool = False) -> jax.Array:
    """Per-row training-score delta of one freshly grown tree:
    ``shrinkage * leaf_value[row_leaf]`` through the streaming lookup
    kernel, with a dried-up tree's (num_leaves <= 1) contribution zeroed
    — the sync path appends a constant tree for it instead
    (gbdt.cpp:421-437). Shared by the pipelined fast step and the
    megastep scan body so both paths stay bit-identical by
    construction."""
    vals = table_lookup(row_leaf[None, :], tree.leaf_value * shrinkage,
                        interpret=interpret)[0]
    if num_rows:
        vals = vals[:num_rows]
    return jnp.where(tree.num_leaves > 1, vals, 0.0)


def add_leaf_values_to_score(score: jax.Array, row_leaf: jax.Array,
                             leaf_value: jax.Array, shrinkage,
                             interpret: bool = False) -> jax.Array:
    """score += shrinkage * leaf_value[row_leaf] via the streaming lookup
    kernel (ref: score_updater.hpp:88 — O(n) leaf-value add). Padding rows
    (leaf -1) receive 0."""
    Rp = score.shape[0]
    vals = table_lookup(row_leaf[None, :], leaf_value,
                        interpret=interpret)[0]
    return score + shrinkage * vals
