"""Single-device tree learner: jit-compiled leaf-wise and depth-wise growth.

TPU-native replacement for the reference SerialTreeLearner + GPU/CUDA learners
(ref: src/treelearner/serial_tree_learner.cpp:159-715,
gpu_tree_learner.cpp:953-1056).  Key design departures, deliberate
(SURVEY.md §7 design stance):

- Per-round state is a dense ``row_leaf: int32[R]`` assignment instead of
  per-leaf index lists (ref DataPartition, data_partition.hpp:21) — static
  shapes for XLA; partition update is one vectorized pass.
- The whole tree grows inside ONE jit-compiled function; no host round trip
  per leaf (the reference GPU learner's D2H-per-leaf wart, SURVEY.md §3.5).
- ``leafwise``: exact reference semantics — global-best leaf split per step
  (ref: serial_tree_learner.cpp:159-210 Train loop), histogram for the
  smaller child + sibling subtraction (ref: :283-323, :423-425).
- ``depthwise``: frontier-batched growth — one masked histogram pass per
  level for all left children at once, splits ranked by gain under the
  num_leaves budget.  This is the TPU-fast path (MXU-friendly batches);
  equivalent to the reference's quality at equal num_leaves on balanced data.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histograms
from ..ops.collectives import record_pmax, record_psum
from ..ops.split import (BestSplit, SplitParams, best_numerical_split,
                         best_numerical_split_cm, best_split_cm,
                         calculate_leaf_output, leaf_gain)
from .tree import TreeArrays, empty_tree

NEG_INF = -jnp.inf


class FeatureMeta(NamedTuple):
    """Static-shape per-feature metadata arrays (device)."""
    num_bin: jax.Array        # int32 [F]
    missing_type: jax.Array   # int32 [F]
    default_bin: jax.Array    # int32 [F]
    monotone: jax.Array       # int32 [F]
    is_cat: jax.Array = None  # bool  [F] (None = all numerical)


def meta_is_cat(meta: "FeatureMeta") -> jax.Array:
    if meta.is_cat is None:
        return jnp.zeros(meta.num_bin.shape, bool)
    return meta.is_cat


def best_split(hist: jax.Array, meta: FeatureMeta, feature_mask: jax.Array,
               params: SplitParams, parent_output: jax.Array,
               has_cat: bool = False, use_bounds: bool = False,
               bound_lo=None, bound_hi=None, leaf_depth=None,
               cegb_delta=None, bound_lo_plane=None,
               bound_hi_plane=None) -> BestSplit:
    """Channel-minor convenience wrapper over the combined numerical +
    categorical scan (ref: feature_histogram.hpp:85 FindBestThreshold)."""
    return best_split_cm(
        hist[..., 0], hist[..., 1], hist[..., 2], meta.num_bin,
        meta.missing_type, meta.default_bin, feature_mask,
        meta_is_cat(meta), meta.monotone, params, parent_output,
        has_cat=has_cat, use_bounds=use_bounds, bound_lo=bound_lo,
        bound_hi=bound_hi, leaf_depth=leaf_depth, cegb_delta=cegb_delta,
        bound_lo_plane=bound_lo_plane, bound_hi_plane=bound_hi_plane)


class NodeMaskCfg(NamedTuple):
    """Per-node feature-mask machinery (ref: col_sampler.hpp:20 ColSampler
    — interaction-constraint filtering + feature_fraction_bynode).

    group_feat: [G, F] bool — constraint groups (one all-True row when no
      interaction constraints).
    groups_with_f: [F] int32 — bitmask of groups containing each feature.
    bynode_k: int32 scalar — features sampled per node (0 = off).
    key: jax PRNG key for by-node sampling.
    """
    group_feat: jax.Array
    groups_with_f: jax.Array
    bynode_k: jax.Array
    key: jax.Array


def make_node_mask_cfg(num_features: int, interaction_constraints,
                       bynode_fraction: float, seed: int) -> NodeMaskCfg:
    import numpy as _np
    groups = [list(g) for g in (interaction_constraints or [])]
    if not groups:
        gf = _np.ones((1, num_features), bool)
    else:
        if len(groups) > 31:
            raise ValueError("at most 31 interaction constraint groups are "
                             "supported")
        gf = _np.zeros((len(groups), num_features), bool)
        for gi, g in enumerate(groups):
            for f in g:
                if 0 <= int(f) < num_features:
                    gf[gi, int(f)] = True
    gwf = _np.zeros((num_features,), _np.int32)
    for gi in range(gf.shape[0]):
        gwf |= _np.where(gf[gi], _np.int32(1 << gi), 0).astype(_np.int32)
    k = 0
    if 0.0 < bynode_fraction < 1.0:
        k = max(1, int(round(num_features * bynode_fraction)))
    return NodeMaskCfg(
        group_feat=jnp.asarray(gf),
        groups_with_f=jnp.asarray(gwf),
        bynode_k=jnp.int32(k),
        key=jax.random.PRNGKey(seed))


def node_feature_mask(cfg: NodeMaskCfg, leaf_groups: jax.Array,
                      node_ids: jax.Array) -> jax.Array:
    """[L, F] allowed-feature mask for each leaf: union of the constraint
    groups still compatible with the leaf's path, intersected with a
    per-NODE random feature sample when bynode_k > 0 (``node_ids`` [L]
    identify the node each leaf was created by, so a leaf's sample is
    stable for its whole lifetime — per-node semantics like the
    reference's ColSampler, not a per-level re-roll)."""
    G, F = cfg.group_feat.shape
    L = leaf_groups.shape[0]
    bits = ((leaf_groups[:, None] >> jnp.arange(G, dtype=jnp.int32)) & 1
            ).astype(jnp.float32)                              # [L, G]
    allowed = (bits @ cfg.group_feat.astype(jnp.float32)) > 0  # [L, F]
    k = cfg.bynode_k

    def with_bynode(allowed):
        keys = jax.vmap(lambda nid: jax.random.fold_in(cfg.key, nid))(
            node_ids.astype(jnp.int32))
        r = jax.vmap(lambda kk: jax.random.uniform(kk, (F,)))(keys)
        r = jnp.where(allowed, r, jnp.inf)
        kth = jnp.sort(r, axis=1)[
            jnp.arange(L), jnp.clip(k - 1, 0, F - 1)]
        return allowed & (r <= kth[:, None])

    return jax.lax.cond(k > 0, with_bynode, lambda a: a, allowed)


def update_leaf_groups(cfg: NodeMaskCfg, leaf_groups, split_feature,
                       sel, left_idx, new_idx):
    """Child group-compatibility bitmasks: parent & groups containing the
    split feature (both children take the same narrowed set)."""
    f_safe = jnp.maximum(split_feature, 0)
    child = leaf_groups & jnp.where(split_feature >= 0,
                                    cfg.groups_with_f[f_safe], -1)
    if left_idx is not None:
        out = _masked_scatter(leaf_groups, left_idx, child, sel)
    else:
        out = jnp.where(sel, child, leaf_groups)
    return _masked_scatter(out, new_idx, child, sel)


def gather_split_info(pool_leaf, f, t, meta: "FeatureMeta",
                      params: SplitParams, parent_output) -> BestSplit:
    """Split record for a GIVEN (feature, threshold) from a leaf's
    histogram (ref: feature_histogram.hpp GatherInfoForThresholdNumerical
    — used by forced splits). default_left=False: missing bins ride right
    and are excluded from the left sums."""
    h = jax.lax.dynamic_index_in_dim(pool_leaf, f, axis=0,
                                     keepdims=False)          # [B, 3]
    B = h.shape[0]
    b_iota = jnp.arange(B, dtype=jnp.int32)
    nb = meta.num_bin[f]
    mt = meta.missing_type[f]
    db = meta.default_bin[f]
    is_missing = (((mt == 1) & (b_iota == db))
                  | ((mt == 2) & (b_iota == nb - 1)))
    left_m = ((b_iota <= t) & ~is_missing)[:, None]
    tot = jnp.sum(h, axis=0)
    lsum = jnp.sum(jnp.where(left_m, h, 0.0), axis=0)
    lg, lh, lc = lsum[0], lsum[1] + 1e-15, lsum[2]
    rg, rh, rc = tot[0] - lg, tot[1] - lsum[1] + 1e-15, tot[2] - lc
    lo = calculate_leaf_output(lg, lh, params, lc, parent_output)
    ro = calculate_leaf_output(rg, rh, params, rc, parent_output)
    shift = leaf_gain(tot[0], tot[1] + 2e-15, params, tot[2],
                      parent_output) + params.min_gain_to_split
    gain = (leaf_gain(lg, lh, params, lc, parent_output)
            + leaf_gain(rg, rh, params, rc, parent_output) - shift)
    return BestSplit(
        feature=f.astype(jnp.int32), threshold=t.astype(jnp.int32),
        default_left=jnp.asarray(False),
        gain=gain, left_output=lo, right_output=ro,
        left_sum_grad=lg, left_sum_hess=lh - 1e-15, left_count=lc,
        right_sum_grad=rg, right_sum_hess=rh - 1e-15, right_count=rc,
        cat_flag=jnp.asarray(False), cat_mask=jnp.zeros((B,), bool))


class BundleCfg(NamedTuple):
    """Device arrays mapping logical features onto EFB bundle columns
    (built from ops/efb.BundleLayout; see that module's docstring).

    flat_idx: [F, B] int32 — index into the flattened [C*B_col] bundle
      histogram for each (feature, bin); invalid bins point at slot 0 and
      are masked by ``valid``.
    valid: [F, B] bool.
    default_bin: [F] int32 (receives the FixHistogram residual mass).
    col_of_feat / offset_of_feat: [F] int32 — routing decode.
    (The per-column bin count travels separately as the static
    ``bundle_col_bins`` grower argument.)
    """
    flat_idx: jax.Array
    valid: jax.Array
    default_bin: jax.Array
    col_of_feat: jax.Array
    offset_of_feat: jax.Array


def bundle_views(bundle_hist: jax.Array, cfg: BundleCfg) -> jax.Array:
    """[S, C, Bc, ch] bundle histograms -> [S, F, B, ch] logical views
    with the FixHistogram default-bin residual (ref: dataset.cpp:1265);
    delegates to the shared ops/fused_level implementation."""
    from ..ops.fused_level import bundle_plane_views
    return bundle_plane_views(bundle_hist, cfg.flat_idx, cfg.valid,
                              cfg.default_bin)


def cegb_delta_matrix(params: SplitParams, coupled_penalty, used_features,
                      leaf_counts, lazy_penalty=None, unused_cnt=None):
    """[S, F] CEGB gain delta: tradeoff*penalty_split*n_leaf plus the
    one-time coupled feature cost for features not yet used in any split,
    plus the per-row LAZY cost — penalty[f] per data point in the leaf
    whose path has not used feature f yet (ref:
    cost_effective_gradient_boosting.hpp:66 DetlaGain; ``unused_cnt``
    [S, F] comes from a segment-sum of the persistent used bitmap)."""
    split_pen = (params.cegb_tradeoff * params.cegb_penalty_split
                 * leaf_counts[:, None])
    feat_pen = params.cegb_tradeoff * jnp.where(used_features, 0.0,
                                                coupled_penalty)[None, :]
    delta = split_pen + feat_pen
    if lazy_penalty is not None:
        delta = delta + (params.cegb_tradeoff * lazy_penalty[None, :]
                         * unused_cnt)
    return delta


def mono_child_bounds(lo, hi, new_lo, new_hi, sel, mono_dir,
                      left_output, right_output, left_idx, new_idx):
    """Per-leaf monotone bound update at split time — the reference's
    BASIC rule (ref: monotone_constraints.hpp:488-500
    BasicLeafConstraints::Update): both children are fenced at
    mid = (left_out + right_out)/2, which guarantees every later leaf in
    the left subtree stays <= mid <= every leaf in the right subtree
    (raw-output fences permit cross-subtree violations — caught in
    round 3). m<0 mirrored; non-monotone splits pass bounds through.
    All arrays [L]; ``sel`` masks the leaves actually split this step."""
    par_lo = lo[left_idx] if left_idx is not None else lo
    par_hi = hi[left_idx] if left_idx is not None else hi
    mid = 0.5 * (left_output + right_output)
    l_hi = jnp.where(mono_dir > 0, jnp.minimum(par_hi, mid), par_hi)
    l_lo = jnp.where(mono_dir < 0, jnp.maximum(par_lo, mid), par_lo)
    r_lo = jnp.where(mono_dir > 0, jnp.maximum(par_lo, mid), par_lo)
    r_hi = jnp.where(mono_dir < 0, jnp.minimum(par_hi, mid), par_hi)
    lo2 = _masked_scatter(new_lo, left_idx, l_lo, sel)         if left_idx is not None else jnp.where(sel, l_lo, new_lo)
    hi2 = _masked_scatter(new_hi, left_idx, l_hi, sel)         if left_idx is not None else jnp.where(sel, l_hi, new_hi)
    lo2 = _masked_scatter(lo2, new_idx, r_lo, sel)
    hi2 = _masked_scatter(hi2, new_idx, r_hi, sel)
    return lo2, hi2


def region_adjacency(q_lo, q_hi, c_lo, c_hi, mask, monotone,
                     per_dim: bool = False):
    """Monotone region adjacency of every leaf box q against C child
    boxes — the ONE implementation of the predicate used by the
    intermediate/advanced machinery (vectorized form of the reference's
    GoUp/GoDown contiguity walk): boxes overlap on every feature but
    one monotone g, and q lies strictly beyond the child on g.

    q_lo/q_hi: [L, F] bin-space boxes; c_lo/c_hi: [C, F]; mask: [L] or
    [L, C] gating which q count; monotone: [F]. Returns (up, dn) as
    [L, C] any-dim booleans, or [L, C, F] per-dim masks with
    ``per_dim=True`` (the advanced mode needs the adjacency feature to
    build its shadow planes)."""
    F = q_lo.shape[1]
    ql = q_lo[:, None, :]
    qh = q_hi[:, None, :]
    cl = c_lo[None, :, :]
    ch = c_hi[None, :, :]
    ov = (ql < ch) & (cl < qh)                       # [L, C, F]
    cnt = jnp.sum(ov.astype(jnp.int32), axis=2)
    ov_except = (cnt[:, :, None] - ov.astype(jnp.int32)) == (F - 1)
    m = mask[:, None] if mask.ndim == 1 else mask
    gate = ov_except & m[:, :, None]
    above = gate & (ql >= ch)
    below = gate & (qh <= cl)
    d = monotone[None, None, :]
    up = ((d > 0) & above) | ((d < 0) & below)
    dn = ((d > 0) & below) | ((d < 0) & above)
    if per_dim:
        return up, dn
    return jnp.any(up, axis=2), jnp.any(dn, axis=2)


def mono_inter_level_update(leaf_value, leaf_lo, leaf_hi, reg_lo, reg_hi,
                            selected, k_of_leaf, feature, threshold,
                            cat_flag, left_out, right_out, monotone,
                            num_leaves_before, n_slots: int):
    """Intermediate-mode bookkeeping for one LEVEL of simultaneous splits
    (ref: monotone_constraints.hpp:514 IntermediateLeafConstraints —
    raw-output fences, region-aware clipping of fresh child outputs
    against adjacent leaves, cross-tree tightening of other leaves).

    The O(rows) routing/histogram work stays batched in the level kernel;
    THIS bookkeeping runs the level's splits SEQUENTIALLY in slot (gain
    rank) order over [L]-sized state — the same ordering the leaf-wise
    grower uses, which is what guarantees every pair of region-adjacent
    leaves ends the level with ordered outputs (simultaneous clipping
    cannot: two fresh children of different parents may both clip only
    against pre-level leaves and stay inverted; chains of fresh leaves
    need the inductive one-at-a-time argument).

    All arrays are [L]-sized ([L, F] for regions); ``k_of_leaf`` ranks
    the selected leaves; the k-th split's right child gets id
    ``num_leaves_before + k``. Returns (leaf_value2, lo2, hi2, reg_lo2,
    reg_hi2, changed) where ``changed`` marks pre-existing leaves whose
    bounds tightened (their cached best splits are stale)."""
    L, F = reg_lo.shape

    def _adj(q_lo, q_hi, mask_q, c_lo, c_hi):
        return region_adjacency(q_lo, q_hi, c_lo, c_hi, mask_q, monotone)

    def body(k, st):
        lv, lo, hi, rlo, rhi, changed = st
        hit = selected & (k_of_leaf == k)
        has = jnp.any(hit)
        l = jnp.argmax(hit)
        new = num_leaves_before + k
        f = jnp.maximum(feature[l], 0)
        t = threshold[l]
        cf = cat_flag[l]
        is_num = ~cf
        o_l0 = left_out[l]
        o_n0 = right_out[l]
        mono_d = jnp.where(is_num, monotone[f], 0)

        # regions: numerical split cuts the parent's box at t+1
        parent_lo = rlo[l]
        parent_hi = rhi[l]
        l_hi_r = parent_hi.at[f].set(jnp.where(is_num, t + 1,
                                               parent_hi[f]))
        n_lo_r = parent_lo.at[f].set(jnp.where(is_num, t + 1,
                                               parent_lo[f]))
        rlo2 = rlo.at[new].set(n_lo_r)
        rhi2 = rhi.at[new].set(parent_hi).at[l].set(l_hi_r)

        c_lo = jnp.stack([parent_lo, n_lo_r])
        c_hi = jnp.stack([l_hi_r, parent_hi])
        active = (jnp.arange(L) < num_leaves_before + k)

        # region-aware clipping vs CURRENT leaves (pre-level leaves AND
        # this level's already-processed children — the sequential order
        # is what covers fresh-fresh adjacency)
        exist = active & (jnp.arange(L) != l)
        q_up, q_dn = _adj(rlo, rhi, exist, c_lo, c_hi)
        qv = lv[:, None]
        c_hi_b = jnp.min(jnp.where(q_up, qv, jnp.inf), axis=0)
        c_lo_b = jnp.max(jnp.where(q_dn, qv, -jnp.inf), axis=0)
        o_l = jnp.clip(o_l0, c_lo_b[0], c_hi_b[0])
        o_n = jnp.clip(o_n0, c_lo_b[1], c_hi_b[1])
        # sibling order must survive the independent clips
        o_n = jnp.where(mono_d > 0, jnp.maximum(o_n, o_l), o_n)
        o_n = jnp.where(mono_d < 0, jnp.minimum(o_n, o_l), o_n)

        lv2 = lv.at[l].set(jnp.where(has, o_l, lv[l]))
        lv2 = lv2.at[new].set(jnp.where(has, o_n, lv2[new]))

        # inherited bounds + raw-output fences (looser than basic's mid)
        # then the adjacency clip bounds, with CLIPPED outputs
        p_lo, p_hi = lo[l], hi[l]
        l_hi = jnp.where(mono_d > 0, jnp.minimum(p_hi, o_n), p_hi)
        l_lo = jnp.where(mono_d < 0, jnp.maximum(p_lo, o_n), p_lo)
        n_lo = jnp.where(mono_d > 0, jnp.maximum(p_lo, o_l), p_lo)
        n_hi = jnp.where(mono_d < 0, jnp.minimum(p_hi, o_l), p_hi)
        lo2 = lo.at[l].set(jnp.maximum(l_lo, c_lo_b[0])) \
            .at[new].set(jnp.maximum(n_lo, c_lo_b[1]))
        hi2 = hi.at[l].set(jnp.minimum(l_hi, c_hi_b[0])) \
            .at[new].set(jnp.minimum(n_hi, c_hi_b[1]))

        # cross-tighten the OTHER leaves by the new (clipped) outputs
        other = active & (jnp.arange(L) != l)
        q_up2, q_dn2 = _adj(rlo2, rhi2, other, c_lo, c_hi)
        co = jnp.stack([o_l, o_n])[None, :]
        lo_cand = jnp.max(jnp.where(q_up2, co, -jnp.inf), axis=1)
        hi_cand = jnp.min(jnp.where(q_dn2, co, jnp.inf), axis=1)
        lo3 = jnp.maximum(lo2, lo_cand)
        hi3 = jnp.minimum(hi2, hi_cand)
        changed2 = changed | (lo3 > lo2) | (hi3 < hi2)

        def keep(_):
            return lv, lo, hi, rlo, rhi, changed
        def take(_):
            return lv2, lo3, hi3, rlo2, rhi2, changed2
        return jax.lax.cond(has, take, keep, None)

    lv, lo, hi, rlo, rhi, changed = jax.lax.fori_loop(
        0, n_slots, body,
        (leaf_value, leaf_lo, leaf_hi, reg_lo, reg_hi,
         jnp.zeros((L,), bool)))
    # fresh children are rescanned by the level flow anyway
    changed = changed & (jnp.arange(L) < num_leaves_before) & ~selected
    return lv, lo, hi, rlo, rhi, changed


def _route_left(bins_col: jax.Array, t: jax.Array, default_left: jax.Array,
                nb: jax.Array, mt: jax.Array, db: jax.Array) -> jax.Array:
    """Binned-data split decision with missing routing
    (ref: dense_bin.hpp Split — NaN bin / zero bin follow default_left)."""
    b = bins_col.astype(jnp.int32)
    missing = (((mt == 1) & (b == db)) | ((mt == 2) & (b == nb - 1)))
    return jnp.where(missing, default_left, b <= t)


def merge_best_over_shards(bs: BestSplit, axis: str,
                           f_offset) -> BestSplit:
    """Global best split per slot across feature-parallel shards
    (ref: parallel_tree_learner.h:191 SyncUpGlobalBestSplit — the 48-byte
    SplitInfo allreduce-max, expressed as pmax + winner-shard pick).
    Local feature indices are globalized with ``f_offset`` first."""
    g = bs.gain
    gmax = record_pmax(g, axis)
    idx = jax.lax.axis_index(axis)
    big = jnp.int32(1 << 30)
    # earliest shard wins ties (matches the reference's rank order)
    winner = jax.lax.pmin(jnp.where(g >= gmax, idx, big), axis)
    mine = idx == winner

    def pick(a):
        m = mine if a.ndim == 1 else mine[:, None]
        z = jnp.where(m, a, jnp.zeros_like(a))
        if a.dtype == jnp.bool_:
            return record_psum(z.astype(jnp.int32), axis) > 0
        return record_psum(z, axis)

    feat_g = jnp.where(bs.feature >= 0,
                       bs.feature + jnp.int32(f_offset), -1)
    out = {f: pick(getattr(bs, f)) for f in bs._fields
           if f not in ("gain", "feature")}
    return BestSplit(feature=pick(feat_g), gain=gmax, **out)


def _merge_best(best: BestSplit, idx0, idx1, new2: BestSplit) -> BestSplit:
    """Scatter a 2-slot BestSplit into positions idx0/idx1 of a pooled one."""
    return BestSplit(*[a.at[idx0].set(b[0]).at[idx1].set(b[1])
                       for a, b in zip(best, new2)])


def _masked_scatter(arr: jax.Array, idx: jax.Array, vals: jax.Array,
                    mask: jax.Array) -> jax.Array:
    """``arr[idx[k]] = vals[k] where mask[k]`` without write collisions:
    masked-out writes are routed to a padding slot (scatter with duplicate
    indices has unspecified order in XLA, so junk writes must not alias real
    ones)."""
    pad_shape = (1,) + arr.shape[1:]
    ext = jnp.concatenate([arr, jnp.zeros(pad_shape, arr.dtype)])
    safe_idx = jnp.where(mask, idx, arr.shape[0])
    ext = ext.at[safe_idx].set(vals)
    return ext[:-1]


def _masked_gain(best: BestSplit, leaf_depth, num_leaves, max_depth: int,
                 max_leaves: int):
    """Gain vector with inactive/over-deep leaves masked out."""
    slot = jnp.arange(max_leaves)
    g = jnp.where(slot < num_leaves, best.gain, NEG_INF)
    if max_depth > 0:
        g = jnp.where(leaf_depth >= max_depth, NEG_INF, g)
    return g


@functools.partial(
    jax.jit,
    static_argnames=("params", "num_leaves", "max_bins", "max_depth",
                     "hist_impl", "psum_axis", "has_cat",
                     "use_mono_bounds", "use_node_masks", "n_forced",
                     "use_bundles", "bundle_col_bins", "mono_mode",
                     "parallel_mode", "top_k"))
def grow_tree_leafwise(bins: jax.Array, gh: jax.Array, meta: FeatureMeta,
                       feature_mask: jax.Array, params: SplitParams,
                       num_leaves: int, max_bins: int, max_depth: int = -1,
                       hist_impl: str = "auto", psum_axis: str = None,
                       has_cat: bool = False, use_mono_bounds: bool = False,
                       use_node_masks: bool = False,
                       node_masks: "NodeMaskCfg" = None,
                       n_forced: int = 0,
                       forced_leaf: jax.Array = None,
                       forced_feat: jax.Array = None,
                       forced_thr: jax.Array = None,
                       use_bundles: bool = False,
                       bundle_cfg: "BundleCfg" = None,
                       bundle_col_bins: int = 0,
                       mono_mode: str = "basic",
                       parallel_mode: str = "data",
                       top_k: int = 20,
                       ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree leaf-wise (best-first), entirely on device.

    With ``psum_axis`` set (running under shard_map over a row-sharded mesh),
    every histogram is allreduced over that mesh axis so all shards see
    GLOBAL counts and make identical split decisions — the TPU formulation
    of the reference's data-parallel learner (ref:
    src/treelearner/data_parallel_tree_learner.cpp:155-189 reduce-scatter +
    SyncUpGlobalBestSplit, collapsed into one psum over ICI).

    Returns (tree arrays, final row→leaf assignment).
    """
    R, F = bins.shape
    if use_bundles:
        # ``bins`` holds EFB bundle columns (ref: src/io/dataset.cpp
        # feature groups); histograms/scans stay logical via the views
        F = bundle_cfg.flat_idx.shape[0]
    L = num_leaves
    B = max_bins

    def _psum(h):
        return record_psum(h, psum_axis) if psum_axis is not None else h

    # voting-parallel under LEAF-WISE growth (ref:
    # voting_parallel_tree_learner.cpp:151-184 — the reference's voting
    # learner composes with standard best-first growth): each step the
    # shards vote their local top_k features on the smaller child's
    # histogram, only the 2*top_k winners' columns are summed over the
    # mesh, and a per-leaf validity plane gates later scans (the
    # sibling-subtraction parent must be globally valid too). With
    # top_k >= F every column wins and the tree reproduces the serial
    # leaf-wise model exactly. Divergence: the vote ranks the SMALLER
    # child's local gains (the larger sibling is reconstructed by
    # subtraction and has no local histogram to rank).
    voting = psum_axis is not None and parallel_mode == "voting"
    W_vote = min(F, 2 * top_k)

    def _exchange_one(hist_local, parent_out1):
        """[1, F, B, 3] local smaller-child histogram ->
        (global [F, B, 3], valid [F])."""
        if not voting:
            return _psum(hist_local)[0], jnp.ones((F,), bool)
        from ..ops.split import per_feature_gains_cm
        fm2 = (feature_mask[None, :] if feature_mask.ndim == 1
               else feature_mask)
        gains = per_feature_gains_cm(
            hist_local[..., 0], hist_local[..., 1], hist_local[..., 2],
            meta.num_bin, meta.missing_type, meta.default_bin, fm2,
            meta_is_cat(meta), meta.monotone, params, parent_out1,
            has_cat=has_cat)
        k = min(top_k, F)
        kth = jnp.sort(gains, axis=1)[:, F - k][:, None]
        votes = (gains >= kth) & jnp.isfinite(gains)
        votes = record_psum(votes.astype(jnp.int32), psum_axis)[0]
        _, w_idx = jax.lax.top_k(votes, W_vote)
        if n_forced > 0:
            # forced-split features must always carry GLOBAL sums: the
            # forced gather reads the pool regardless of the vote
            # (duplicates in w_idx are harmless — same values re-set)
            w_idx = jnp.concatenate([w_idx, forced_feat])
        sub = record_psum(jnp.take(hist_local[0], w_idx, axis=0),
                           psum_axis)
        hist2 = jnp.zeros_like(hist_local[0]).at[w_idx].set(sub)
        valid = jnp.zeros((F,), bool).at[w_idx].set(True)
        return hist2, valid

    def _hist(slot_vec, num_slots):
        if use_bundles:
            hb = build_histograms(bins, gh, slot_vec, num_slots=num_slots,
                                  num_bins=bundle_col_bins, impl=hist_impl)
            return bundle_views(hb, bundle_cfg)
        return build_histograms(bins, gh, slot_vec, num_slots=num_slots,
                                num_bins=B, impl=hist_impl)

    tree = empty_tree(L, B)
    row_leaf = jnp.zeros((R,), jnp.int32)

    # root histogram: every row targets slot 0 (always a FULL exchange —
    # one F*B*3 payload per tree; voting applies from the first split)
    pool = jnp.zeros((L, F, B, 3), jnp.float32)
    pool_valid = jnp.ones((L, F), bool)
    root_hist = _psum(_hist(row_leaf, 1))
    pool = pool.at[0].set(root_hist[0])

    root_g = jnp.sum(root_hist[0, 0, :, 0])
    root_h = jnp.sum(root_hist[0, 0, :, 1])
    root_c = jnp.sum(root_hist[0, 0, :, 2])
    root_out = calculate_leaf_output(root_g, root_h, params, root_c, 0.0)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(root_out),
        leaf_count=tree.leaf_count.at[0].set(root_c),
        leaf_weight=tree.leaf_weight.at[0].set(root_h))

    leaf_lo = jnp.full((L,), -jnp.inf, jnp.float32)
    leaf_hi = jnp.full((L,), jnp.inf, jnp.float32)
    leaf_groups = jnp.full((L,), -1, jnp.int32)
    # intermediate/advanced monotone modes track per-leaf axis-aligned bin
    # regions [lo, hi) so bound tightening can reach non-sibling leaves
    # (ref: monotone_constraints.hpp:514 IntermediateLeafConstraints);
    # advanced additionally scans fresh children with per-(feature,
    # bin-segment) bound PLANES (ref: :856 AdvancedLeafConstraints)
    inter = use_mono_bounds and mono_mode in ("intermediate", "advanced")
    adv = use_mono_bounds and mono_mode == "advanced"
    reg_lo = jnp.zeros((L, F), jnp.int32)
    reg_hi = jnp.broadcast_to(meta.num_bin[None, :], (L, F)) \
        .astype(jnp.int32)

    def _scan_mask(lg_rows, node_ids):
        m = feature_mask[None, :] if feature_mask.ndim == 1 else feature_mask
        if use_node_masks:
            m = m & node_feature_mask(node_masks, lg_rows, node_ids)
        return jnp.broadcast_to(m, (lg_rows.shape[0],
                                    meta.num_bin.shape[0]))

    root_best = best_split(
        pool[:1], meta,
        _scan_mask(leaf_groups[:1], jnp.zeros((1,), jnp.int32)), params,
        tree.leaf_value[:1],
        has_cat=has_cat, use_bounds=use_mono_bounds,
        bound_lo=leaf_lo[:1], bound_hi=leaf_hi[:1],
        leaf_depth=tree.leaf_depth[:1])
    best = BestSplit(*[jnp.zeros((L,) + a.shape[1:], a.dtype).at[0].set(a[0])
                       for a in root_best])
    best = best._replace(gain=best.gain.at[1:].set(NEG_INF))

    leaf_parent_node = jnp.full((L,), -1, jnp.int32)
    leaf_is_left = jnp.zeros((L,), bool)

    State = Tuple  # (tree, row_leaf, pool, best, parent_node, is_left)

    def body(i, state):
        (tree, row_leaf, pool, pool_valid, best, lpn, lil, leaf_lo,
         leaf_hi, leaf_groups, reg_lo, reg_hi) = state
        gains = _masked_gain(best, tree.leaf_depth, tree.num_leaves,
                             max_depth, L)
        l = jnp.argmax(gains).astype(jnp.int32)
        do_split = gains[l] > 0.0
        if n_forced > 0:
            # forced top-of-tree splits (ref: serial_tree_learner.cpp:455
            # ForceSplits — BFS through the forced-split JSON, bypassing
            # the gain-based choice; the schedule is precomputed on host).
            # Invalid forced splits (an empty child) are skipped like the
            # reference; lax.cond keeps the gather off the hot path once
            # the schedule is exhausted.
            safe_i = jnp.minimum(i, n_forced - 1)
            fl = forced_leaf[safe_i]
            ff = forced_feat[safe_i]
            ft = forced_thr[safe_i]

            def _forced_info(_):
                return gather_split_info(pool[fl], ff, ft, meta, params,
                                         tree.leaf_value[fl])

            def _no_info(_):
                z = jnp.float32(0)
                return BestSplit(
                    jnp.int32(-1), jnp.int32(0), jnp.asarray(False),
                    jnp.float32(NEG_INF), z, z, z, z, z, z, z, z,
                    jnp.asarray(False), jnp.zeros((B,), bool))

            finfo = jax.lax.cond(i < n_forced, _forced_info, _no_info,
                                 None)
            forced_ok = ((i < n_forced)
                         & (finfo.left_count >= 1)
                         & (finfo.right_count >= 1))
            l = jnp.where(forced_ok, fl, l)
            do_split = do_split | forced_ok

        def split_branch(op):
            (tree, row_leaf, pool, pool_valid, best, lpn, lil, leaf_lo,
             leaf_hi, leaf_groups, reg_lo, reg_hi) = op
            new = tree.num_leaves
            f = best.feature[l]
            t = best.threshold[l]
            dl = best.default_left[l]
            cf = best.cat_flag[l]
            cm = best.cat_mask[l]
            bsl = BestSplit(*[a[l] for a in best])
            if n_forced > 0:
                f = jnp.where(forced_ok, finfo.feature, f)
                t = jnp.where(forced_ok, finfo.threshold, t)
                dl = jnp.where(forced_ok, False, dl)
                cf = jnp.where(forced_ok, False, cf)
                bsl = BestSplit(*[jnp.where(forced_ok, a, b)
                                  for a, b in zip(finfo, bsl)])

            # --- node bookkeeping (ref: tree.h:62 Tree::Split) ---
            write_left = (lpn[l] >= 0) & lil[l]
            write_right = (lpn[l] >= 0) & ~lil[l]
            pn_safe = jnp.maximum(lpn[l], 0)
            lc = tree.left_child
            rc = tree.right_child
            lc = lc.at[pn_safe].set(jnp.where(write_left, i, lc[pn_safe]))
            rc = rc.at[pn_safe].set(jnp.where(write_right, i, rc[pn_safe]))
            lc = lc.at[i].set(-l - 1)      # ~leaf
            rc = rc.at[i].set(-new - 1)
            new_depth = tree.leaf_depth[l] + 1
            tree2 = tree._replace(
                num_leaves=tree.num_leaves + 1,
                split_feature=tree.split_feature.at[i].set(f),
                threshold_bin=tree.threshold_bin.at[i].set(t),
                default_left=tree.default_left.at[i].set(dl),
                cat_flag=tree.cat_flag.at[i].set(cf),
                cat_mask=tree.cat_mask.at[i].set(cm),
                left_child=lc, right_child=rc,
                split_gain=tree.split_gain.at[i].set(bsl.gain),
                internal_value=tree.internal_value.at[i].set(tree.leaf_value[l]),
                internal_count=tree.internal_count.at[i].set(tree.leaf_count[l]),
                internal_weight=tree.internal_weight.at[i].set(
                    tree.leaf_weight[l]),
                leaf_value=tree.leaf_value.at[l].set(bsl.left_output)
                                          .at[new].set(bsl.right_output),
                leaf_count=tree.leaf_count.at[l].set(bsl.left_count)
                                          .at[new].set(bsl.right_count),
                leaf_weight=tree.leaf_weight.at[l].set(bsl.left_sum_hess)
                                            .at[new].set(bsl.right_sum_hess),
                leaf_depth=tree.leaf_depth.at[l].set(new_depth)
                                          .at[new].set(new_depth),
            )
            lpn2 = lpn.at[l].set(i).at[new].set(i)
            lil2 = lil.at[l].set(True).at[new].set(False)

            # --- partition update (ref: data_partition.hpp Split) ---
            if use_bundles:
                f_safe = jnp.maximum(f, 0)
                raw = jnp.take(bins, bundle_cfg.col_of_feat[f_safe],
                               axis=1, mode="clip").astype(jnp.int32)
                off = bundle_cfg.offset_of_feat[f_safe]
                in_win = (raw >= off) & (raw < off + meta.num_bin[f_safe])
                bins_col = jnp.where(in_win, raw - off,
                                     bundle_cfg.default_bin[f_safe])
            else:
                bins_col = jnp.take(bins, f, axis=1, mode="clip")
            go_left = _route_left(bins_col, t, dl, meta.num_bin[f],
                                  meta.missing_type[f], meta.default_bin[f])
            if has_cat:
                cat_left = jnp.take(cm, bins_col.astype(jnp.int32),
                                    mode="clip")
                go_left = jnp.where(cf, cat_left, go_left)
            on_leaf = row_leaf == l
            row_leaf2 = jnp.where(on_leaf & ~go_left, new, row_leaf)

            # --- smaller-child histogram + sibling subtraction ---
            target_is_left = bsl.left_count <= bsl.right_count
            target_leaf = jnp.where(target_is_left, l, new)
            slot = jnp.where(row_leaf2 == target_leaf, 0, -1)
            hist_t, valid_t = _exchange_one(_hist(slot, 1),
                                            tree.leaf_value[l][None])
            hist_sib = pool[l] - hist_t
            pool2 = pool.at[l].set(jnp.where(target_is_left, hist_t, hist_sib))
            pool2 = pool2.at[new].set(jnp.where(target_is_left, hist_sib,
                                                hist_t))
            # validity: the exchanged child is valid on winner columns;
            # the subtraction sibling additionally needs a valid parent
            v_sib = pool_valid[l] & valid_t
            pool_valid2 = pool_valid.at[l].set(
                jnp.where(target_is_left, valid_t, v_sib))
            pool_valid2 = pool_valid2.at[new].set(
                jnp.where(target_is_left, v_sib, valid_t))

            # --- monotone bound propagation for the two children ---
            # basic: both children fenced at mid=(l+r)/2 (ref:
            # BasicLeafConstraints::Update, monotone_constraints.hpp:488)
            # — the fence is what guarantees left-subtree <= mid <=
            # right-subtree for every later descendant.
            # intermediate: raw-output fences (UpdateConstraintsWithOutputs
            # :544) — looser, compensated by the cross-tree tightening +
            # stale-leaf recompute below.
            if use_mono_bounds:
                mono_d = jnp.where(f >= 0, meta.monotone[jnp.maximum(f, 0)],
                                   0)
                # the reference updates constraints only for numerical
                # splits in BOTH modes (BasicLeafConstraints::Update and
                # UpdateConstraintsWithOutputs gate on is_numerical_split,
                # monotone_constraints.hpp:488,547); a categorical split
                # on a monotone feature must not fence the children
                mono_d = jnp.where(bsl.cat_flag, 0, mono_d)
                p_lo, p_hi = leaf_lo[l], leaf_hi[l]
                if inter:
                    fence_l = bsl.right_output   # raw opposite outputs
                    fence_r = bsl.left_output
                else:
                    fence_l = fence_r = 0.5 * (bsl.left_output
                                               + bsl.right_output)
                l_hi = jnp.where(mono_d > 0, jnp.minimum(p_hi, fence_l),
                                 p_hi)
                l_lo = jnp.where(mono_d < 0, jnp.maximum(p_lo, fence_l),
                                 p_lo)
                r_lo = jnp.where(mono_d > 0, jnp.maximum(p_lo, fence_r),
                                 p_lo)
                r_hi = jnp.where(mono_d < 0, jnp.minimum(p_hi, fence_r),
                                 p_hi)
                leaf_lo2 = leaf_lo.at[l].set(l_lo).at[new].set(r_lo)
                leaf_hi2 = leaf_hi.at[l].set(l_hi).at[new].set(r_hi)
            else:
                leaf_lo2, leaf_hi2 = leaf_lo, leaf_hi

            # --- interaction-group narrowing for the two children ---
            if use_node_masks:
                child_g = leaf_groups[l] & jnp.where(
                    f >= 0, node_masks.groups_with_f[jnp.maximum(f, 0)], -1)
                leaf_groups2 = leaf_groups.at[l].set(child_g) \
                    .at[new].set(child_g)
            else:
                leaf_groups2 = leaf_groups

            # --- child best splits ---
            child_hist = jnp.stack([pool2[l], pool2[new]])
            parent_out2 = jnp.stack([tree2.leaf_value[l],
                                     tree2.leaf_value[new]])
            bs2 = best_split(
                child_hist, meta,
                _scan_mask(jnp.stack([leaf_groups2[l], leaf_groups2[new]]),
                           jnp.stack([2 * (i + 1) + 1, 2 * (i + 1)]))
                & jnp.stack([pool_valid2[l], pool_valid2[new]]),
                params, parent_out2,
                has_cat=has_cat, use_bounds=use_mono_bounds,
                bound_lo=jnp.stack([leaf_lo2[l], leaf_lo2[new]]),
                bound_hi=jnp.stack([leaf_hi2[l], leaf_hi2[new]]),
                leaf_depth=jnp.stack([tree2.leaf_depth[l],
                                      tree2.leaf_depth[new]]))
            best2 = _merge_best(best, l, new, bs2)

            # --- intermediate mode: region cut + cross-tree tightening +
            # stale-leaf best-split recompute (ref:
            # monotone_constraints.hpp:514-720 Update/GoUp/GoDown,
            # serial_tree_learner.cpp:706-714). Regions make the
            # reference's up-and-down contiguity walk a vectorized
            # adjacency test: leaf q is constrained by new child c on
            # monotone feature g when their regions overlap on every
            # other feature and q lies strictly beyond c on g.
            reg_lo2, reg_hi2 = reg_lo, reg_hi
            if inter:
                is_num = ~cf
                parent_lo = reg_lo[l]
                parent_hi = reg_hi[l]
                fs = jnp.maximum(f, 0)
                l_hi_r = parent_hi.at[fs].set(
                    jnp.where(is_num, t + 1, parent_hi[fs]))
                n_lo_r = parent_lo.at[fs].set(
                    jnp.where(is_num, t + 1, parent_lo[fs]))
                # BOTH region coordinates of the fresh slot must be
                # written — its stored values are the init placeholder
                reg_lo2 = reg_lo.at[new].set(n_lo_r)
                reg_hi2 = reg_hi.at[new].set(parent_hi).at[l].set(l_hi_r)

                c_lo = jnp.stack([parent_lo, n_lo_r])           # [2, F]
                c_hi = jnp.stack([l_hi_r, parent_hi])
                active = jnp.arange(L) < tree.num_leaves

                def _adj(q_lo, q_hi, mask_q):
                    return region_adjacency(q_lo, q_hi, c_lo, c_hi,
                                            mask_q, meta.monotone)

                # --- region-aware child clipping: a child strictly beyond
                # an EXISTING leaf must respect that leaf's output NOW —
                # inheritance alone misses leaves the parent straddled
                # (ref: the per-feature constraint recompute,
                # monotone_constraints.hpp RecomputeConstraintsIfNeeded)
                lo_before, hi_before = leaf_lo2, leaf_hi2
                exist = active & (jnp.arange(L) != l)
                q_up, q_dn = _adj(reg_lo, reg_hi, exist)        # [L, 2]
                qv = tree.leaf_value[:, None]
                c_hi_b = jnp.min(jnp.where(q_up, qv, jnp.inf), axis=0)
                c_lo_b = jnp.max(jnp.where(q_dn, qv, -jnp.inf), axis=0)
                o_l = jnp.clip(bsl.left_output, c_lo_b[0], c_hi_b[0])
                o_n = jnp.clip(bsl.right_output, c_lo_b[1], c_hi_b[1])
                # sibling order must survive the independent clips
                mono_d2 = jnp.where(f >= 0, meta.monotone[fs], 0)
                num_mono = is_num & (mono_d2 != 0)
                o_n = jnp.where(num_mono & (mono_d2 > 0),
                                jnp.maximum(o_n, o_l), o_n)
                o_n = jnp.where(num_mono & (mono_d2 < 0),
                                jnp.minimum(o_n, o_l), o_n)
                tree2 = tree2._replace(
                    leaf_value=tree2.leaf_value.at[l].set(o_l)
                                               .at[new].set(o_n))
                leaf_lo2 = leaf_lo2.at[l].max(c_lo_b[0]) \
                                   .at[new].max(c_lo_b[1])
                leaf_hi2 = leaf_hi2.at[l].min(c_hi_b[0]) \
                                   .at[new].min(c_hi_b[1])
                # sibling fences re-applied with the CLIPPED outputs
                leaf_hi2 = leaf_hi2.at[l].min(jnp.where(
                    num_mono & (mono_d2 > 0), o_n, jnp.inf))
                leaf_lo2 = leaf_lo2.at[l].max(jnp.where(
                    num_mono & (mono_d2 < 0), o_n, -jnp.inf))
                leaf_lo2 = leaf_lo2.at[new].max(jnp.where(
                    num_mono & (mono_d2 > 0), o_l, -jnp.inf))
                leaf_hi2 = leaf_hi2.at[new].min(jnp.where(
                    num_mono & (mono_d2 < 0), o_l, jnp.inf))

                # --- cross-tree tightening of the OTHER leaves by the new
                # (clipped) child outputs
                other = active & (jnp.arange(L) != l)
                other = other.at[new].set(False)
                q_up2, q_dn2 = _adj(reg_lo2, reg_hi2, other)
                co = jnp.stack([o_l, o_n])[None, :]
                lo_cand = jnp.max(jnp.where(q_up2, co, -jnp.inf), axis=1)
                hi_cand = jnp.min(jnp.where(q_dn2, co, jnp.inf), axis=1)
                leaf_lo2 = jnp.maximum(leaf_lo2, lo_cand)
                leaf_hi2 = jnp.minimum(leaf_hi2, hi_cand)
                changed = (leaf_lo2 > lo_before) | (leaf_hi2 < hi_before)

                def _rescan(b):
                    node_ids = 2 * (lpn2 + 1) + lil2.astype(jnp.int32)
                    # under voting only globally-summed pool columns may
                    # be rescanned (pool_valid2 gates them)
                    bs_all = best_split(
                        pool2, meta,
                        _scan_mask(leaf_groups2, node_ids) & pool_valid2,
                        params,
                        tree2.leaf_value, has_cat=has_cat,
                        use_bounds=True, bound_lo=leaf_lo2,
                        bound_hi=leaf_hi2, leaf_depth=tree2.leaf_depth)

                    def merge(old, newv):
                        m = changed if old.ndim == 1 else changed[:, None]
                        return jnp.where(m, newv, old)
                    return BestSplit(*[merge(o, n)
                                       for o, n in zip(b, bs_all)])

                best2 = jax.lax.cond(jnp.any(changed), _rescan,
                                     lambda b: b, best2)

                if adv:
                    # ---- ADVANCED: re-derive the two fresh children's
                    # best splits with per-(feature, bin-segment) bound
                    # planes built from the CURRENT leaves (ref:
                    # monotone_constraints.hpp:856 — constraints are
                    # computed fresh at evaluation time by descending to
                    # the constraining leaves; the dense analog is a
                    # min/max-reduction over every leaf's shadow mask).
                    # Stale-leaf rescans above keep the scalar
                    # (intermediate-grade) bounds — a conservative
                    # refinement gap, never a monotonicity risk: safety
                    # lives in the apply-time adjacency clip.
                    act2 = jnp.arange(L) < tree2.num_leaves
                    tgt = jnp.stack([l, new])                     # [2]
                    excl = (jnp.arange(L)[:, None] != tgt[None, :])                         & act2[:, None]                           # [L, 2]
                    up_d, dn_d = region_adjacency(
                        reg_lo2, reg_hi2,
                        jnp.stack([reg_lo2[l], reg_lo2[new]]),
                        jnp.stack([reg_hi2[l], reg_hi2[new]]),
                        excl, meta.monotone, per_dim=True)
                    any_up = jnp.any(up_d, axis=2)                # [L, 2]
                    any_dn = jnp.any(dn_d, axis=2)
                    b_i3 = jnp.arange(B, dtype=jnp.int32)[None, None, :]
                    inr = ((reg_lo2[:, :, None] <= b_i3)
                           & (b_i3 < reg_hi2[:, :, None]))        # [L,F,B]
                    ap_up = (up_d[:, :, :, None]
                             | (inr[:, None, :, :]
                                & any_up[:, :, None, None]))      # [L,2,F,B]
                    ap_dn = (dn_d[:, :, :, None]
                             | (inr[:, None, :, :]
                                & any_dn[:, :, None, None]))
                    vq4 = tree2.leaf_value[:, None, None, None]
                    hi_pl = jnp.min(jnp.where(ap_up, vq4, jnp.inf),
                                    axis=0)                       # [2,F,B]
                    lo_pl = jnp.max(jnp.where(ap_dn, vq4, -jnp.inf),
                                    axis=0)
                    bs_adv = best_split(
                        child_hist, meta,
                        _scan_mask(jnp.stack([leaf_groups2[l],
                                              leaf_groups2[new]]),
                                   jnp.stack([2 * (i + 1) + 1,
                                              2 * (i + 1)]))
                        & jnp.stack([pool_valid2[l], pool_valid2[new]]),
                        params,
                        jnp.stack([tree2.leaf_value[l],
                                   tree2.leaf_value[new]]),
                        has_cat=has_cat, use_bounds=True,
                        bound_lo=jnp.stack([leaf_lo2[l], leaf_lo2[new]]),
                        bound_hi=jnp.stack([leaf_hi2[l], leaf_hi2[new]]),
                        bound_lo_plane=lo_pl, bound_hi_plane=hi_pl,
                        leaf_depth=jnp.stack([tree2.leaf_depth[l],
                                              tree2.leaf_depth[new]]))
                    best2 = _merge_best(best2, l, new, bs_adv)
            return (tree2, row_leaf2, pool2, pool_valid2, best2, lpn2,
                    lil2, leaf_lo2, leaf_hi2, leaf_groups2, reg_lo2,
                    reg_hi2)

        return jax.lax.cond(do_split, split_branch, lambda op: op,
                            (tree, row_leaf, pool, pool_valid, best, lpn,
                             lil, leaf_lo, leaf_hi, leaf_groups, reg_lo,
                             reg_hi))

    state = (tree, row_leaf, pool, pool_valid, best, leaf_parent_node,
             leaf_is_left, leaf_lo, leaf_hi, leaf_groups, reg_lo, reg_hi)
    out = jax.lax.fori_loop(0, L - 1, body, state)
    tree, row_leaf = out[0], out[1]
    return tree, row_leaf


@functools.partial(
    jax.jit,
    static_argnames=("params", "num_leaves", "max_bins", "max_depth",
                     "hist_impl", "psum_axis", "has_cat", "parallel_mode",
                     "top_k", "use_mono_bounds", "use_node_masks",
                     "use_cegb", "use_bundles", "bundle_col_bins",
                     "mono_mode", "use_cegb_lazy"))
def grow_tree_depthwise(bins: jax.Array, gh: jax.Array, meta: FeatureMeta,
                        feature_mask: jax.Array, params: SplitParams,
                        num_leaves: int, max_bins: int, max_depth: int = -1,
                        hist_impl: str = "segment", psum_axis: str = None,
                        has_cat: bool = False, parallel_mode: str = "data",
                        top_k: int = 20, route_bins: jax.Array = None,
                        route_meta: FeatureMeta = None,
                        feature_offset=None, use_mono_bounds: bool = False,
                        use_node_masks: bool = False,
                        node_masks: "NodeMaskCfg" = None,
                        use_cegb: bool = False,
                        cegb_coupled: jax.Array = None,
                        cegb_used: jax.Array = None,
                        use_bundles: bool = False,
                        bundle_cfg: "BundleCfg" = None,
                        bundle_col_bins: int = 0,
                        mono_mode: str = "basic",
                        use_cegb_lazy: bool = False,
                        cegb_lazy: jax.Array = None,
                        cegb_used_rf: jax.Array = None,
                        ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree depth-wise (frontier-batched) — the TPU throughput mode.

    Each level: one masked histogram pass builds all left-child histograms at
    once (slots via ``leaf_to_slot``), siblings come from subtraction, and all
    frontier leaves whose gain survives the num_leaves budget split together.

    ``psum_axis``: see grow_tree_leafwise — data-parallel allreduce of the
    per-level histogram batch over the mesh axis.

    ``parallel_mode`` (with psum_axis set):
    - "data": rows sharded, full-histogram allreduce (the default).
    - "feature": features sharded (``bins`` holds this shard's columns,
      ``route_bins``/``route_meta`` the full replicated matrix, and
      ``feature_offset`` this shard's first global column). No histogram
      comm at all; per-level best splits are merged across shards
      (ref: feature_parallel_tree_learner.cpp:60-77).
    - "voting": rows sharded; each level's shards vote for their top_k
      features by local gain and only the 2*top_k vote winners' histogram
      columns are summed over the mesh — the level payload drops from
      F*B*3 to 2*top_k*B*3 (ref: voting_parallel_tree_learner.cpp:151-184
      GlobalVoting/CopyLocalHistogram; divergence: winners are chosen per
      LEVEL as the union of per-slot votes, not per leaf). Histogram pool
      entries for non-winner features are invalid and masked out of later
      scans via a per-leaf validity plane.
    """
    R, F = bins.shape
    if use_bundles:
        # ``bins`` holds EFB bundle columns; logical feature count comes
        # from the mapping (ref: src/io/dataset.cpp feature groups)
        F = bundle_cfg.flat_idx.shape[0]
    L = num_leaves
    B = max_bins
    n_levels = max_depth if max_depth > 0 else max(1, (L - 1).bit_length() + 1)
    # a level can at most double the leaves; cap levels at L-1 splits total
    n_levels = min(n_levels, L - 1)
    W = min(F, 2 * top_k)

    def _psum(h):
        return record_psum(h, psum_axis) if psum_axis is not None else h

    def _exchange(hist, parent_out):
        """Level histogram exchange -> (globally-valid hist, valid [F])."""
        all_valid = jnp.ones((F,), bool)
        if psum_axis is None or parallel_mode == "data":
            return _psum(hist), all_valid
        if parallel_mode == "feature":
            return hist, all_valid         # local features are complete
        # voting: local gains -> per-slot top_k votes -> global top-W cols
        # (categorical features rank by their categorical gain since
        # round 4 — ops/split.per_feature_gains_cm)
        from ..ops.split import per_feature_gains_cm
        gains = per_feature_gains_cm(
            hist[..., 0], hist[..., 1], hist[..., 2], meta.num_bin,
            meta.missing_type, meta.default_bin, feature_mask,
            meta_is_cat(meta), meta.monotone, params, parent_out,
            has_cat=has_cat)
        k = min(top_k, F)
        kth = jnp.sort(gains, axis=1)[:, F - k][:, None]
        votes = (gains >= kth) & jnp.isfinite(gains)
        votes = record_psum(votes.astype(jnp.int32), psum_axis)
        score_f = jnp.sum(votes, axis=0)                     # [F]
        _, w_idx = jax.lax.top_k(score_f, W)
        sub = record_psum(jnp.take(hist, w_idx, axis=1), psum_axis)
        hist2 = jnp.zeros_like(hist).at[:, w_idx].set(sub)
        valid = jnp.zeros((F,), bool).at[w_idx].set(True)
        return hist2, valid

    def _hist(slot_vec, num_slots):
        """Histogram pass; EFB mode histograms the bundle columns then
        reassembles per-feature views (ref: dataset.cpp feature groups +
        :1265 FixHistogram)."""
        if use_bundles:
            hb = build_histograms(bins, gh, slot_vec, num_slots=num_slots,
                                  num_bins=bundle_col_bins, impl=hist_impl)
            return bundle_views(hb, bundle_cfg)
        return build_histograms(bins, gh, slot_vec, num_slots=num_slots,
                                num_bins=B, impl=hist_impl)

    tree = empty_tree(L, B)
    row_leaf = jnp.zeros((R,), jnp.int32)
    pool = jnp.zeros((L, F, B, 3), jnp.float32)
    pool_valid = jnp.zeros((L, F), bool)
    root_local = _hist(row_leaf, 1)
    root_hist, root_valid = _exchange(root_local, jnp.zeros((1,)))
    pool = pool.at[0].set(root_hist[0])
    pool_valid = pool_valid.at[0].set(root_valid)
    root_g = jnp.sum(root_hist[0, 0, :, 0])
    root_h = jnp.sum(root_hist[0, 0, :, 1])
    root_c = jnp.sum(root_hist[0, 0, :, 2])
    root_out = calculate_leaf_output(root_g, root_h, params, root_c, 0.0)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(root_out),
        leaf_count=tree.leaf_count.at[0].set(root_c),
        leaf_weight=tree.leaf_weight.at[0].set(root_h))

    leaf_parent_node = jnp.full((L,), -1, jnp.int32)
    leaf_is_left = jnp.zeros((L,), bool)
    num_nodes = jnp.int32(0)

    leaf_lo = jnp.full((L,), -jnp.inf, jnp.float32)
    leaf_hi = jnp.full((L,), jnp.inf, jnp.float32)
    leaf_groups = jnp.full((L,), -1, jnp.int32)   # all groups compatible
    used_f = (cegb_used if (use_cegb and cegb_used is not None)
              else jnp.zeros((F,), bool))
    # intermediate monotone mode: per-leaf bin-space regions (the stale-
    # leaf recompute is free here — all_best rescans every leaf per level
    # with the tightened bounds)
    inter = use_mono_bounds and mono_mode == "intermediate"
    reg_lo = jnp.zeros((L, F), jnp.int32)
    reg_hi = jnp.broadcast_to(meta.num_bin[None, :], (L, F)) \
        .astype(jnp.int32)

    def all_best(pool, tree, pool_valid, leaf_lo, leaf_hi, leaf_groups,
                 node_ids, used_f, row_leaf=None, used_rf=None):
        mask2d = feature_mask[None, :] & pool_valid
        if use_node_masks:
            mask2d = mask2d & node_feature_mask(node_masks, leaf_groups,
                                                node_ids)
        delta = None
        if use_cegb:
            lazy_kw = {}
            if use_cegb_lazy:
                # per-(leaf, feature) count of rows whose path has not
                # used the feature (ref: the lazy bitmap of
                # cost_effective_gradient_boosting.hpp:22)
                unused = jax.ops.segment_sum(
                    (~used_rf).astype(jnp.float32), row_leaf,
                    num_segments=L)
                lazy_kw = dict(lazy_penalty=cegb_lazy, unused_cnt=unused)
            delta = cegb_delta_matrix(params, cegb_coupled, used_f,
                                      tree.leaf_count, **lazy_kw)
        bs = best_split(pool, meta, mask2d, params,
                        tree.leaf_value, has_cat=has_cat,
                        use_bounds=use_mono_bounds, bound_lo=leaf_lo,
                        bound_hi=leaf_hi, leaf_depth=tree.leaf_depth,
                        cegb_delta=delta)
        if parallel_mode == "feature" and psum_axis is not None:
            bs = merge_best_over_shards(bs, psum_axis, feature_offset)
        return bs

    # persistent per-(row, feature) lazy-CEGB bitmap (placeholder when
    # the mode is off so the scan carry keeps a fixed structure)
    used_rf = (cegb_used_rf if use_cegb_lazy
               else jnp.zeros((1, 1), bool))
    best = all_best(pool, tree, pool_valid, leaf_lo, leaf_hi, leaf_groups,
                    jnp.zeros((L,), jnp.int32), used_f,
                    row_leaf=row_leaf, used_rf=used_rf)
    best = best._replace(gain=jnp.where(jnp.arange(L) == 0, best.gain,
                                        NEG_INF))
    r_bins = bins if route_bins is None else route_bins
    r_meta = meta if route_meta is None else route_meta

    def level(carry, _):
        (tree, row_leaf, pool, pool_valid, best, lpn, lil,
         num_nodes, leaf_lo, leaf_hi, leaf_groups, used_f,
         reg_lo, reg_hi, used_rf) = carry
        gains = _masked_gain(best, tree.leaf_depth, tree.num_leaves,
                             max_depth, L)
        budget = L - tree.num_leaves
        # rank leaves by gain; selected = valid gain within budget
        order = jnp.argsort(-gains)
        rank = jnp.zeros((L,), jnp.int32).at[order].set(
            jnp.arange(L, dtype=jnp.int32))
        selected = (gains > 0.0) & (rank < budget)
        n_sel = jnp.sum(selected.astype(jnp.int32))

        def do_level(op):
            (tree, row_leaf, pool, pool_valid, best, lpn, lil,
             num_nodes, leaf_lo, leaf_hi, leaf_groups, used_f,
             reg_lo, reg_hi, used_rf) = op
            # new leaf ids: k-th selected leaf (by slot order) gets
            # num_leaves + k; node ids num_nodes + k
            sel_i32 = selected.astype(jnp.int32)
            k_of_leaf = jnp.cumsum(sel_i32) - sel_i32  # rank among selected
            new_of_leaf = jnp.where(selected, tree.num_leaves + k_of_leaf, -1)
            node_of_leaf = jnp.where(selected, num_nodes + k_of_leaf, -1)

            # --- vectorized node bookkeeping over selected leaves ---
            slots = jnp.arange(L)
            f_l = best.feature
            t_l = best.threshold
            dl_l = best.default_left
            cf_l = best.cat_flag
            cm_l = best.cat_mask
            new_depth = tree.leaf_depth + 1

            def scatter_nodes(tree, lpn, lil):
                # masked scatter of per-split node records at node_of_leaf
                def w(arr, vals):
                    return _masked_scatter(arr, node_of_leaf, vals, selected)
                sf = w(tree.split_feature, f_l)
                tb = w(tree.threshold_bin, t_l)
                dfl = w(tree.default_left, dl_l)
                cfw = w(tree.cat_flag, cf_l)
                cmw = w(tree.cat_mask, cm_l)
                sg = w(tree.split_gain, best.gain)
                iv = w(tree.internal_value, tree.leaf_value)
                ic = w(tree.internal_count, tree.leaf_count)
                iw = w(tree.internal_weight, tree.leaf_weight)
                lc = w(tree.left_child, -slots - 1)
                rc = w(tree.right_child, -new_of_leaf - 1)
                # parent pointers of split leaves now point at new nodes
                wl = selected & (lpn >= 0) & lil
                wr = selected & (lpn >= 0) & ~lil
                lc = _masked_scatter(lc, lpn, node_of_leaf, wl)
                rc = _masked_scatter(rc, lpn, node_of_leaf, wr)
                lpn2 = jnp.where(selected, node_of_leaf, lpn)
                lil2 = jnp.where(selected, True, lil)
                lpn2 = _masked_scatter(lpn2, new_of_leaf, node_of_leaf,
                                       selected)
                lil2 = _masked_scatter(lil2, new_of_leaf,
                                       jnp.zeros((L,), bool), selected)
                tree2 = tree._replace(
                    split_feature=sf, threshold_bin=tb, default_left=dfl,
                    cat_flag=cfw, cat_mask=cmw,
                    split_gain=sg, internal_value=iv, internal_count=ic,
                    internal_weight=iw, left_child=lc, right_child=rc)
                return tree2, lpn2, lil2

            tree2, lpn2, lil2 = scatter_nodes(tree, lpn, lil)

            # --- vectorized partition update: one gather per row ---
            # (feature-parallel mode routes on the full replicated matrix
            # since the winning column may belong to another shard)
            l_row = row_leaf
            sel_row = selected[l_row]
            f_row = jnp.maximum(f_l[l_row], 0)  # -1 (no split) rows are masked
            if use_bundles:
                col_row = bundle_cfg.col_of_feat[f_row]
                raw = jnp.take_along_axis(
                    r_bins, col_row[:, None].astype(jnp.int32),
                    axis=1)[:, 0].astype(jnp.int32)
                off = bundle_cfg.offset_of_feat[f_row]
                nb_row = r_meta.num_bin[f_row]
                in_win = (raw >= off) & (raw < off + nb_row)
                # out-of-window rows were encoded as bundle-default: they
                # carry the feature's MOST FREQUENT bin (where the
                # FixHistogram residual went), not the zero bin
                bins_row = jnp.where(in_win, raw - off,
                                     bundle_cfg.default_bin[f_row])
            else:
                bins_row = jnp.take_along_axis(
                    r_bins, f_row[:, None].astype(jnp.int32), axis=1)[:, 0]
            go_left = _route_left(bins_row, t_l[l_row], dl_l[l_row],
                                  r_meta.num_bin[f_row],
                                  r_meta.missing_type[f_row],
                                  r_meta.default_bin[f_row])
            if has_cat:
                cat_left = cm_l[l_row, bins_row.astype(jnp.int32)]
                go_left = jnp.where(cf_l[l_row], cat_left, go_left)
            row_leaf2 = jnp.where(sel_row & ~go_left, new_of_leaf[l_row],
                                  row_leaf)
            if use_cegb_lazy:
                # rows in a split leaf mark the split feature as used on
                # their path (persists across trees, ref: the lazy
                # bitmap update in CostEfficientGradientBoosting::
                # UpdateUsedFeature)
                used_rf2 = used_rf | (
                    (sel_row & (f_l[l_row] >= 0))[:, None]
                    & (jnp.arange(F, dtype=jnp.int32)[None, :]
                       == f_row[:, None]))
            else:
                used_rf2 = used_rf

            # --- one histogram pass for all LEFT children (kept old ids) ---
            leaf_to_slot = jnp.where(selected, k_of_leaf, -1)
            row_slot = jnp.where(sel_row & (row_leaf2 == row_leaf),
                                 leaf_to_slot[l_row], -1)
            hist_local = _hist(row_slot, L)
            hist_left, lvl_valid = _exchange(hist_local, tree2.leaf_value)

            # scatter: pool[l] = left hist, pool[new] = parent - left;
            # validity follows (sibling subtraction only holds where BOTH
            # the parent and this level's exchange are globally summed)
            gathered_left = hist_left[jnp.where(selected, k_of_leaf, 0)]
            parent_hist = pool[jnp.where(selected, slots, 0)]
            parent_val = pool_valid[jnp.where(selected, slots, 0)]
            pool2 = _masked_scatter(pool, slots, gathered_left, selected)
            pool2 = _masked_scatter(pool2, new_of_leaf,
                                    parent_hist - gathered_left, selected)
            lvl_valid_rows = jnp.broadcast_to(lvl_valid[None, :], (L, F))
            pv2 = _masked_scatter(pool_valid, slots, lvl_valid_rows,
                                  selected)
            pv2 = _masked_scatter(pv2, new_of_leaf,
                                  parent_val & lvl_valid_rows, selected)

            # --- leaf stats ---
            def upd2(arr, lv, rv):
                arr = _masked_scatter(arr, slots, lv, selected)
                return _masked_scatter(arr, new_of_leaf, rv, selected)
            if inter:
                # sequential per-split bookkeeping in slot order — see
                # mono_inter_level_update; clipped child outputs replace
                # the raw scan outputs
                (lv_inter, leaf_lo2, leaf_hi2, reg_lo2, reg_hi2,
                 _changed) = mono_inter_level_update(
                    tree.leaf_value, leaf_lo, leaf_hi, reg_lo, reg_hi,
                    selected, k_of_leaf, best.feature, best.threshold,
                    best.cat_flag, best.left_output, best.right_output,
                    meta.monotone, tree.num_leaves, L)
                new_leaf_value = lv_inter
            else:
                new_leaf_value = upd2(tree2.leaf_value, best.left_output,
                                      best.right_output)
                reg_lo2, reg_hi2 = reg_lo, reg_hi
            tree2 = tree2._replace(
                num_leaves=tree.num_leaves + n_sel,
                leaf_value=new_leaf_value,
                leaf_count=upd2(tree2.leaf_count, best.left_count,
                                best.right_count),
                leaf_weight=upd2(tree2.leaf_weight, best.left_sum_hess,
                                 best.right_sum_hess),
                leaf_depth=upd2(tree2.leaf_depth, new_depth, new_depth),
            )

            if use_mono_bounds and not inter:
                mono_dir = jnp.where(
                    best.feature >= 0,
                    meta.monotone[jnp.maximum(best.feature, 0)], 0)
                # the reference updates constraints only for NUMERICAL
                # splits (BasicLeafConstraints::Update gates on
                # is_numerical_split)
                mono_dir = jnp.where(best.cat_flag, 0, mono_dir)
                leaf_lo2, leaf_hi2 = mono_child_bounds(
                    leaf_lo, leaf_hi, leaf_lo, leaf_hi, selected, mono_dir,
                    best.left_output, best.right_output, slots, new_of_leaf)
            elif not use_mono_bounds:
                leaf_lo2, leaf_hi2 = leaf_lo, leaf_hi
            if use_node_masks:
                leaf_groups2 = update_leaf_groups(
                    node_masks, leaf_groups, best.feature, selected, slots,
                    new_of_leaf)
            else:
                leaf_groups2 = leaf_groups
            if use_cegb:
                chosen = _masked_scatter(
                    jnp.zeros((F,), bool),
                    jnp.maximum(f_l, 0).astype(jnp.int32),
                    jnp.ones((L,), bool), selected & (f_l >= 0))
                used_f2 = used_f | chosen
            else:
                used_f2 = used_f
            # a leaf's sampling identity: creating node id + side bit
            node_ids2 = 2 * (lpn2 + 1) + lil2.astype(jnp.int32)
            best2 = all_best(pool2, tree2, pv2, leaf_lo2, leaf_hi2,
                             leaf_groups2, node_ids2, used_f2,
                             row_leaf=row_leaf2, used_rf=used_rf2)
            active = jnp.arange(L) < tree2.num_leaves
            best2 = best2._replace(gain=jnp.where(active, best2.gain, NEG_INF))
            return (tree2, row_leaf2, pool2, pv2, best2, lpn2, lil2,
                    num_nodes + n_sel, leaf_lo2, leaf_hi2, leaf_groups2,
                    used_f2, reg_lo2, reg_hi2, used_rf2)

        carry2 = jax.lax.cond(n_sel > 0, do_level, lambda op: op,
                              (tree, row_leaf, pool, pool_valid, best, lpn,
                               lil, num_nodes, leaf_lo, leaf_hi,
                               leaf_groups, used_f, reg_lo, reg_hi,
                               used_rf))
        return carry2, None

    carry = (tree, row_leaf, pool, pool_valid, best, leaf_parent_node,
             leaf_is_left, num_nodes, leaf_lo, leaf_hi, leaf_groups, used_f,
             reg_lo, reg_hi, used_rf)
    out = jax.lax.scan(level, carry, None, length=n_levels)[0]
    tree, row_leaf = out[0], out[1]
    if use_cegb_lazy:
        return tree, row_leaf, out[14]
    return tree, row_leaf
