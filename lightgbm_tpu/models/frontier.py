"""Frontier-batched tree growth — the TPU performance path.

Replaces the hot part of the reference serial/GPU tree learners (ref:
src/treelearner/serial_tree_learner.cpp:159-453, gpu_tree_learner.cpp:953)
with a fully on-device, level-unrolled grower:

- levels are unrolled in Python so every level gets a jit-specialized slot
  count S_d = min(2^d, L): early levels cost almost nothing instead of
  paying the num_leaves-sized histogram of the scan-based formulation;
- histograms come from the Pallas kernel (ops/pallas_histogram.py) on TPU,
  falling back to the XLA one-hot/segment formulations elsewhere;
- per-level state is channel-major ([3, L, F, B] histogram pool as separate
  planes) — TPU relayouts of channel-minor [..., 3] arrays proved ~100x more
  expensive than the arithmetic they feed;
- the smaller child of each split is histogrammed, the sibling comes from
  parent - child (ref: serial_tree_learner.cpp:423-425 subtraction trick);
- routing reads feature columns from a transposed [F, R] copy of the bin
  matrix (contiguous column loads instead of per-row gathers).

Tree bookkeeping (node arrays) mirrors models/learner.py's depthwise grower.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.histogram import build_histograms
from ..ops.pallas_histogram import HAS_PALLAS, build_histograms_pallas_cm
from ..ops.split import BestSplit, SplitParams, best_numerical_split_cm, \
    calculate_leaf_output
from .learner import FeatureMeta, NEG_INF, _masked_gain, _masked_scatter
from .tree import TreeArrays, empty_tree


def _hist_level(bins_i32, gh3, row_slot, S, Bp, impl, psum_axis):
    """[3, S, F, B] channel-major histogram planes for one level."""
    if impl == "pallas":
        g, h, c = build_histograms_pallas_cm(bins_i32, gh3, row_slot,
                                             num_slots=S, num_bins=Bp)
    else:
        hist = build_histograms(bins_i32.astype(jnp.int32), gh3, row_slot,
                                num_slots=S, num_bins=Bp, impl=impl)
        g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
    if psum_axis is not None:
        g = jax.lax.psum(g, psum_axis)
        h = jax.lax.psum(h, psum_axis)
        c = jax.lax.psum(c, psum_axis)
    return g, h, c


@functools.partial(
    jax.jit,
    static_argnames=("params", "num_leaves", "max_bins", "max_depth",
                     "hist_impl", "psum_axis", "slot_cap"))
def grow_tree_frontier(bins_i32: jax.Array, bins_T: jax.Array,
                       gh3: jax.Array, meta: FeatureMeta,
                       feature_mask: jax.Array, params: SplitParams,
                       num_leaves: int, max_bins: int, max_depth: int = -1,
                       hist_impl: str = "pallas", psum_axis: str = None,
                       slot_cap: int = 64,
                       ) -> Tuple[TreeArrays, jax.Array]:
    """Grow one tree level by level (lax.scan over a uniform body).

    ``slot_cap`` bounds how many leaves split per level pass (the per-level
    Pallas slot count); num_leaves > slot_cap just takes extra passes.
    Fully specializing each level's slot count compiles faster kernels but
    blows up XLA program size at 255 leaves — the scanned uniform body is
    the robust middle ground.

    Args:
      bins_i32: [R, Fp] int32 binned rows (feature-padded for the kernel).
      bins_T: [Fp, R] int32 transposed copy (fast column loads for routing).
      gh3: [R, 3] float32 (grad, hess, weight).

    Returns (TreeArrays, row_leaf).
    """
    R, Fp = bins_i32.shape
    L = num_leaves
    B = max_bins
    S_cap = min(slot_cap, L)
    n_levels = max_depth if max_depth > 0 else max(1, (L - 1).bit_length() + 1)
    n_levels = min(n_levels, L - 1)
    # slot_cap < frontier width means one level of the balanced tree can
    # need several passes
    extra = max(0, (L - 1 + S_cap - 1) // S_cap - n_levels)
    n_levels = n_levels + extra

    tree = empty_tree(L, B)
    row_leaf = jnp.zeros((R,), jnp.int32)
    pool_g = jnp.zeros((L, Fp, B), jnp.float32)
    pool_h = jnp.zeros((L, Fp, B), jnp.float32)
    pool_c = jnp.zeros((L, Fp, B), jnp.float32)

    g0, h0, c0 = _hist_level(bins_i32, gh3, row_leaf, 8, B, hist_impl,
                             psum_axis)
    pool_g = pool_g.at[0].set(g0[0])
    pool_h = pool_h.at[0].set(h0[0])
    pool_c = pool_c.at[0].set(c0[0])
    root_g = jnp.sum(g0[0, 0, :])
    root_h = jnp.sum(h0[0, 0, :])
    root_c = jnp.sum(c0[0, 0, :])
    root_out = calculate_leaf_output(root_g, root_h, params, root_c, 0.0)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(root_out),
        leaf_count=tree.leaf_count.at[0].set(root_c),
        leaf_weight=tree.leaf_weight.at[0].set(root_h))

    def all_best(pg, ph, pc, tree):
        return best_numerical_split_cm(
            pg, ph, pc, meta.num_bin, meta.missing_type, meta.default_bin,
            feature_mask, meta.monotone, params, tree.leaf_value)

    best = all_best(pool_g, pool_h, pool_c, tree)
    best = best._replace(gain=jnp.where(jnp.arange(L) == 0, best.gain,
                                        NEG_INF))
    lpn = jnp.full((L,), -1, jnp.int32)
    lil = jnp.zeros((L,), bool)
    num_nodes = jnp.int32(0)

    state = (tree, row_leaf, pool_g, pool_h, pool_c, best, lpn, lil,
             num_nodes)

    def level_step(state, _):
        return _one_level(state, bins_i32, bins_T, gh3, meta, feature_mask,
                          params, all_best, L, B, S_cap, max_depth,
                          hist_impl, psum_axis), None

    state, _ = jax.lax.scan(level_step, state, None, length=n_levels)
    tree, row_leaf = state[0], state[1]
    return tree, row_leaf


def leaf_value_lookup(leaf_value: jax.Array, row_leaf: jax.Array,
                      num_leaves: int) -> jax.Array:
    """score contribution per row WITHOUT a per-row gather: a where-chain
    over the (small) leaf table — ~100x faster than jnp.take on TPU for
    [R]-from-[L] lookups."""
    def body(l, out):
        return jnp.where(row_leaf == l, leaf_value[l], out)
    init = jnp.zeros(row_leaf.shape, leaf_value.dtype)
    return jax.lax.fori_loop(0, num_leaves, body, init)


def _one_level(state, bins_i32, bins_T, gh3, meta, feature_mask, params,
               all_best, L, B, S_d, max_depth, hist_impl, psum_axis):
    tree, row_leaf, pool_g, pool_h, pool_c, best, lpn, lil, num_nodes = state
    R = row_leaf.shape[0]
    gains = _masked_gain(best, tree.leaf_depth, tree.num_leaves, max_depth, L)
    budget = L - tree.num_leaves
    order = jnp.argsort(-gains)
    rank = jnp.zeros((L,), jnp.int32).at[order].set(
        jnp.arange(L, dtype=jnp.int32))
    selected = (gains > 0.0) & (rank < budget) \
        & (rank < S_d)  # cap splits at this level's slot budget
    n_sel = jnp.sum(selected.astype(jnp.int32))

    def do_level(op):
        (tree, row_leaf, pool_g, pool_h, pool_c, best, lpn, lil,
         num_nodes) = op
        sel_i32 = selected.astype(jnp.int32)
        k_of_leaf = jnp.cumsum(sel_i32) - sel_i32
        new_of_leaf = jnp.where(selected, tree.num_leaves + k_of_leaf, -1)
        node_of_leaf = jnp.where(selected, num_nodes + k_of_leaf, -1)

        slots = jnp.arange(L)
        f_l = best.feature
        t_l = best.threshold
        dl_l = best.default_left
        new_depth = tree.leaf_depth + 1

        def w(arr, vals):
            return _masked_scatter(arr, node_of_leaf, vals, selected)
        sf = w(tree.split_feature, f_l)
        tb = w(tree.threshold_bin, t_l)
        dfl = w(tree.default_left, dl_l)
        sg = w(tree.split_gain, best.gain)
        iv = w(tree.internal_value, tree.leaf_value)
        ic = w(tree.internal_count, tree.leaf_count)
        iw = w(tree.internal_weight, tree.leaf_weight)
        lc = w(tree.left_child, -slots - 1)
        rc = w(tree.right_child, -new_of_leaf - 1)
        wl = selected & (lpn >= 0) & lil
        wr = selected & (lpn >= 0) & ~lil
        lc = _masked_scatter(lc, lpn, node_of_leaf, wl)
        rc = _masked_scatter(rc, lpn, node_of_leaf, wr)
        lpn2 = jnp.where(selected, node_of_leaf, lpn)
        lil2 = jnp.where(selected, True, lil)
        lpn2 = _masked_scatter(lpn2, new_of_leaf, node_of_leaf, selected)
        lil2 = _masked_scatter(lil2, new_of_leaf, jnp.zeros((L,), bool),
                               selected)
        tree2 = tree._replace(
            split_feature=sf, threshold_bin=tb, default_left=dfl,
            split_gain=sg, internal_value=iv, internal_count=ic,
            internal_weight=iw, left_child=lc, right_child=rc)

        # ---- routing + per-level slot assignment in ONE loop over slots.
        # All [R]-from-[L] table lookups become scalar reads inside the loop
        # (per-row gathers run at ~30 ns/row on TPU — the loop's contiguous
        # column loads + wheres are ~100x cheaper).
        left_smaller = best.left_count <= best.right_count     # [L]
        leaf_of_slot = _masked_scatter(
            jnp.zeros((S_d,), jnp.int32),
            jnp.minimum(k_of_leaf, S_d - 1), slots.astype(jnp.int32),
            selected & (k_of_leaf < S_d))

        def route_one(k, carry):
            row_leaf2, row_slot = carry
            leaf = leaf_of_slot[k]
            feat = jnp.maximum(f_l[leaf], 0)
            col = jax.lax.dynamic_index_in_dim(bins_T, feat, axis=0,
                                               keepdims=False)  # [R]
            t = t_l[leaf]
            dl = dl_l[leaf]
            nb = meta.num_bin[feat]
            mt = meta.missing_type[feat]
            db = meta.default_bin[feat]
            b = col.astype(jnp.int32)
            missing = (((mt == 1) & (b == db)) | ((mt == 2) & (b == nb - 1)))
            left = jnp.where(missing, dl, b <= t)
            on_leaf = (row_leaf == leaf) & (k < n_sel)
            new_id = new_of_leaf[leaf]
            row_leaf2 = jnp.where(on_leaf & ~left, new_id, row_leaf2)
            # smaller child of this split gets histogram slot k
            small_is_left = left_smaller[leaf]
            is_small = jnp.where(small_is_left, left, ~left)
            row_slot = jnp.where(on_leaf & is_small, k, row_slot)
            return row_leaf2, row_slot

        row_leaf2, row_slot = jax.lax.fori_loop(
            0, S_d, route_one,
            (row_leaf, jnp.full((R,), -1, jnp.int32)))

        # ---- histogram the SMALLER child per split; sibling by subtraction
        hg, hh, hc = _hist_level(bins_i32, gh3, row_slot, S_d, B,
                                 hist_impl, psum_axis)

        # pool updates: small child gets fresh hist, sibling = parent - small
        k_safe = jnp.minimum(k_of_leaf, S_d - 1)
        got_g = hg[k_safe]
        got_h = hh[k_safe]
        got_c = hc[k_safe]
        par_g = pool_g[jnp.where(selected, slots, 0)]
        par_h = pool_h[jnp.where(selected, slots, 0)]
        par_c = pool_c[jnp.where(selected, slots, 0)]
        sib_g = par_g - got_g
        sib_h = par_h - got_h
        sib_c = par_c - got_c
        # left child keeps the old leaf id; right child gets new id
        left_g = jnp.where(left_smaller[:, None, None], got_g, sib_g)
        left_h = jnp.where(left_smaller[:, None, None], got_h, sib_h)
        left_c = jnp.where(left_smaller[:, None, None], got_c, sib_c)
        right_g = jnp.where(left_smaller[:, None, None], sib_g, got_g)
        right_h = jnp.where(left_smaller[:, None, None], sib_h, got_h)
        right_c = jnp.where(left_smaller[:, None, None], sib_c, got_c)
        pool_g2 = _masked_scatter(pool_g, slots, left_g, selected)
        pool_g2 = _masked_scatter(pool_g2, new_of_leaf, right_g, selected)
        pool_h2 = _masked_scatter(pool_h, slots, left_h, selected)
        pool_h2 = _masked_scatter(pool_h2, new_of_leaf, right_h, selected)
        pool_c2 = _masked_scatter(pool_c, slots, left_c, selected)
        pool_c2 = _masked_scatter(pool_c2, new_of_leaf, right_c, selected)

        def upd2(arr, lv, rv):
            arr = _masked_scatter(arr, slots, lv, selected)
            return _masked_scatter(arr, new_of_leaf, rv, selected)
        tree2 = tree2._replace(
            num_leaves=tree.num_leaves + n_sel,
            leaf_value=upd2(tree2.leaf_value, best.left_output,
                            best.right_output),
            leaf_count=upd2(tree2.leaf_count, best.left_count,
                            best.right_count),
            leaf_weight=upd2(tree2.leaf_weight, best.left_sum_hess,
                             best.right_sum_hess),
            leaf_depth=upd2(tree2.leaf_depth, new_depth, new_depth),
        )

        best2 = all_best(pool_g2, pool_h2, pool_c2, tree2)
        active = jnp.arange(L) < tree2.num_leaves
        best2 = best2._replace(gain=jnp.where(active, best2.gain, NEG_INF))
        return (tree2, row_leaf2, pool_g2, pool_h2, pool_c2, best2, lpn2,
                lil2, num_nodes + n_sel)

    return jax.lax.cond(n_sel > 0, do_level, lambda op: op, state)
