"""Batched on-device prediction.

Replaces the reference's per-row host tree walk for batch predict
(ref: predictor.hpp:30 Predictor, gbdt_prediction.cpp — OpenMP over rows,
pointer-chasing per tree) with stacked [T, nodes] tree tensors packed
ONCE per model state and a jit-compiled scan that advances every row one
tree level per pass.  Two routing variants share the scan:

- :class:`DevicePredictor` — **binned** routing: host-side binning
  through the training BinMappers (exactly the training-time
  quantization, so routing is bit-identical to the host walk), then
  threshold-bin compares on device.  Needs a live training dataset.
- :class:`RawDevicePredictor` — **raw-value** routing for boosters
  WITHOUT training BinMappers (model-file loads, the serving residency
  case): float32 compares against thresholds pre-rounded by
  :func:`threshold_to_f32` so any float32-representable input routes
  bit-identically to the float64 host compare; per-node missing
  semantics decoded from the model's decision_type bitfield.

Scores accumulate in float32 on device (the host path carries float64;
differences are ~1e-7 relative).  The Booster picks a device path only
above ``pred_device_min_work`` rows×trees; exact-parity flows (model IO
round-trips, SHAP) keep the host walk.  The jitted runners live at
module scope so every predictor instance — and every resident model in
``lightgbm_tpu.serve`` — shares ONE XLA cache entry per shape signature:
re-packing an evicted model recompiles nothing.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# raw-variant categorical vocabulary cap: the per-node mask becomes a
# [T, N, C] bool tensor over raw category values; a vocabulary past this
# is a degradation (host walk), not an allocation surprise
RAW_CAT_VALUE_CAP = 4096
# ... and so is a mask whose TOTAL size explodes (the vocabulary cap
# bounds C, but T*N*C can still blow up on deep many-tree models with a
# wide vocab): 64M bool elements ~= 64 MB
RAW_CAT_MASK_MAX_ELEMS = 64 * 1024 * 1024


def _round_up_pow2(n: int) -> int:
    return 1 << max(1, (n - 1).bit_length())


def threshold_to_f32(thr: np.ndarray) -> np.ndarray:
    """Largest float32 <= each float64 threshold.  With thresholds
    rounded this way, ``v32 <= t32`` in float32 agrees with
    ``float64(v32) <= t64`` for EVERY float32 value v32 (same trick as
    binning.BinMapper._bounds_f32), so raw-value device routing is
    bit-identical to the host walk whenever the input is float32-
    representable — the documented serving contract."""
    t64 = np.asarray(thr, np.float64)
    t32 = t64.astype(np.float32)
    over = t32.astype(np.float64) > t64
    t32[over] = np.nextafter(t32[over], np.float32(-np.inf))
    return t32


# ---------------------------------------------------------------------------
# Shared jitted runners (module scope: one XLA cache entry per shape
# signature across ALL predictor instances / resident serve models).
# ---------------------------------------------------------------------------

def _run_binned_body(bins, sf, tb, dl, lc, rc, lv, tids, cf, cm,
                     num_bin, missing, default_bin, *, k, max_steps):
    from ..ops.predict import route_rows_to_leaves
    R = bins.shape[0]

    def tree_step(raw, xs):
        if cf is None:
            sf_t, tb_t, dl_t, lc_t, rc_t, lv_t, tid = xs
            cf_t = cm_t = None
        else:
            (sf_t, tb_t, dl_t, lc_t, rc_t, lv_t, tid, cf_t, cm_t) = xs
        leaves = route_rows_to_leaves(
            bins, sf_t, tb_t, dl_t, lc_t, rc_t, num_bin,
            missing, default_bin, max_steps, cf_t, cm_t)
        return raw.at[tid].add(lv_t[leaves]), None

    raw0 = jnp.zeros((k, R), jnp.float32)
    xs = (sf, tb, dl, lc, rc, lv, tids)
    if cf is not None:
        xs = xs + (cf, cm)
    raw, _ = jax.lax.scan(tree_step, raw0, xs)
    return raw


def _run_raw_body(values, sf, th, dl, mt, lc, rc, lv, tids, cf, cm,
                  *, k, max_steps):
    from ..ops.predict import route_raw_rows_to_leaves
    R = values.shape[0]

    def tree_step(raw, xs):
        if cf is None:
            sf_t, th_t, dl_t, mt_t, lc_t, rc_t, lv_t, tid = xs
            cf_t = cm_t = None
        else:
            (sf_t, th_t, dl_t, mt_t, lc_t, rc_t, lv_t, tid, cf_t,
             cm_t) = xs
        leaves = route_raw_rows_to_leaves(
            values, sf_t, th_t, dl_t, mt_t, lc_t, rc_t, max_steps,
            cf_t, cm_t)
        return raw.at[tid].add(lv_t[leaves]), None

    raw0 = jnp.zeros((k, R), jnp.float32)
    xs = (sf, th, dl, mt, lc, rc, lv, tids)
    if cf is not None:
        xs = xs + (cf, cm)
    raw, _ = jax.lax.scan(tree_step, raw0, xs)
    return raw


_RUN_FNS = {}


def stacked_run_fn(variant: str):
    """The shared jitted runner for a variant ('binned' | 'raw').  The
    encoded-rows operand (argnum 0, freshly materialized per call) is
    donated where the backend honors donation (TPU/GPU), so the padded
    request buffer is recycled into scratch instead of held across the
    dispatch."""
    fn = _RUN_FNS.get(variant)
    if fn is None:
        from ..parallel.mesh import donate_argnums
        body = _run_binned_body if variant == "binned" else _run_raw_body
        fn = jax.jit(body, static_argnames=("k", "max_steps"),
                     donate_argnums=donate_argnums(0))
        _RUN_FNS[variant] = fn
    return fn


class _StackedPredictor:
    """Shared chunked predict loop over a packed tree stack."""

    variant = ""

    def __init__(self):
        self.ok = True
        self.reason = ""
        self.k = 1
        self.max_steps = 1
        self._packed: List[jax.Array] = []

    @property
    def packed_nbytes(self) -> int:
        """Device bytes held by the packed tree tensors (the serve
        residency manager's accounting unit)."""
        return int(sum(int(getattr(a, "nbytes", 0) or 0)
                       for a in self._packed if a is not None))

    def encode(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def run_args(self, lo: int, hi: int) -> Tuple:
        """Packed-tensor operand tuple for ``stacked_run_fn(variant)``
        covering trees [lo, hi) — everything after the encoded rows."""
        raise NotImplementedError

    def _predict_chunk(self, enc: jax.Array, lo: int, hi: int) -> jax.Array:
        return stacked_run_fn(self.variant)(
            enc, *self.run_args(lo, hi), k=self.k,
            max_steps=self.max_steps)

    def predict_raw(self, X: np.ndarray, lo: int, hi: int,
                    chunk_rows: int = 2_000_000) -> np.ndarray:
        """Sum of leaf values of trees [lo, hi) per class, [k, R] float64.

        scipy sparse input is densified PER CHUNK (prediction routes on
        logical values/bins regardless of the training-side bundle
        storage)."""
        try:
            import scipy.sparse as sp
            sparse_in = sp.issparse(X)
        except ImportError:  # pragma: no cover
            sparse_in = False
        if sparse_in:
            X = X.tocsr()
            chunk_rows = min(chunk_rows, 262_144)
        n = X.shape[0]
        out = np.zeros((self.k, n), np.float64)
        for c0 in range(0, n, chunk_rows):
            sl = slice(c0, min(n, c0 + chunk_rows))
            Xc = X[sl].toarray() if sparse_in else X[sl]
            enc = jnp.asarray(self.encode(Xc))
            raw = self._predict_chunk(enc, lo, hi)
            out[:, sl] = np.asarray(raw, np.float64)
        return out


class DevicePredictor(_StackedPredictor):
    """Stacked-tree device predictor routing on TRAINING BINS."""

    variant = "binned"

    def __init__(self, models: List, ds, num_tree_per_iteration: int):
        """models: HostTree list; ds: TpuDataset (mappers + used_features)."""
        super().__init__()
        self.ds = ds
        self.k = num_tree_per_iteration
        T = len(models)
        if T == 0:
            self.ok, self.reason = False, "no_trees"
            return
        if any(getattr(t, "is_linear", False) for t in models):
            # linear leaves compute base + coeff·x from RAW values; the
            # stacked leaf_value lookup cannot represent them
            self.ok, self.reason = False, "linear_tree"
            return
        if not ds.used_features:
            # every feature binned trivial (single-leaf-only models):
            # the routing kernel has no bin columns to gather from
            self.ok, self.reason = False, "no_used_features"
            return
        N = max(max(t.num_internal for t in models), 1)
        L = max(max(t.num_leaves for t in models), 2)
        B = int(max(m.num_bin for m in ds.mappers)) if ds.mappers else 2
        depth = 1
        sf = np.zeros((T, N), np.int32)
        tb = np.zeros((T, N), np.int32)
        dl = np.zeros((T, N), bool)
        lc = np.full((T, N), -1, np.int32)
        rc = np.full((T, N), -1, np.int32)
        lv = np.zeros((T, L), np.float32)
        has_cat = any(t.cat_threshold for t in models)
        cf = np.zeros((T, N), bool) if has_cat else None
        cm = np.zeros((T, N, B), bool) if has_cat else None

        for ti, t in enumerate(models):
            ni = t.num_internal
            if ni == 0:
                lv[ti, 0] = t.leaf_value[0]
                continue
            for i in range(ni):
                real_f = int(t.split_feature[i])
                inner = ds.inner_feature_index(real_f)
                if inner < 0:  # split on a filtered feature: cannot happen
                    self.ok = False  # for self-trained models; bail out
                    self.reason = "filtered_feature"
                    return
                sf[ti, i] = inner
                m = ds.mappers[real_f]
                d = int(t.decision_type[i])
                is_cat = bool(d & 1)
                if is_cat:
                    cf[ti, i] = True
                    # value bitset -> bin mask through the category vocab
                    cat_idx = int(t.threshold[i])
                    lo = t.cat_boundaries[cat_idx]
                    hi = t.cat_boundaries[cat_idx + 1]
                    words = t.cat_threshold[lo:hi]
                    for b, cat in enumerate(m.bin_2_categorical):
                        if cat < 0:
                            continue
                        w, bit = divmod(int(cat), 32)
                        if w < len(words) and (words[w] >> bit) & 1:
                            cm[ti, i, b] = True
                else:
                    tb[ti, i] = int(t.threshold_bin[i]) if \
                        len(t.threshold_bin) > i else \
                        int(m.value_to_bin(t.threshold[i]))
                    dl[ti, i] = bool(d & 2)
            lc[ti, :ni] = t.left_child
            rc[ti, :ni] = t.right_child
            lv[ti, :t.num_leaves] = t.leaf_value
            ld = getattr(t, "leaf_depth", None)
            # model-file trees parse with an all-zero leaf_depth (the
            # text format does not store depth): fall back to the
            # num_internal bound, never to a fake depth of 0
            if ld is not None and len(ld) and int(np.max(ld)) > 0:
                depth = max(depth, int(np.max(ld)))
            else:
                depth = max(depth, ni)

        self.max_steps = _round_up_pow2(depth + 1)
        self.sf = jnp.asarray(sf)
        self.tb = jnp.asarray(tb)
        self.dl = jnp.asarray(dl)
        self.lc = jnp.asarray(lc)
        self.rc = jnp.asarray(rc)
        self.lv = jnp.asarray(lv)
        self.cf = jnp.asarray(cf) if has_cat else None
        self.cm = jnp.asarray(cm) if has_cat else None
        self.num_bin = jnp.asarray(ds.num_bin_per_feat)
        self.missing = jnp.asarray(ds.missing_types)
        self.default_bin = jnp.asarray(
            np.array([ds.mappers[j].default_bin for j in ds.used_features],
                     np.int32))
        self._packed = [self.sf, self.tb, self.dl, self.lc, self.rc,
                        self.lv, self.cf, self.cm, self.num_bin,
                        self.missing, self.default_bin]
        # shape/dtype of the encoded-rows operand (the serve engine's
        # compile signature includes these: the tree-stack shapes alone
        # do not determine the compiled program)
        self.enc_width = ds.num_features
        self.enc_dtype = "int32"

    # ------------------------------------------------------------------
    def _bin_rows(self, X: np.ndarray) -> np.ndarray:
        ds = self.ds
        out = np.empty((X.shape[0], ds.num_features), np.int32)
        for k, j in enumerate(ds.used_features):
            out[:, k] = ds.mappers[j].value_to_bin(
                np.asarray(X[:, j], np.float64))
        return out

    def encode(self, X: np.ndarray) -> np.ndarray:
        return self._bin_rows(X)

    def run_args(self, lo: int, hi: int) -> Tuple:
        # full-range slice: hand out the packed arrays themselves — a
        # jnp slice materializes a device COPY, which doubled the serve
        # engine's true residency (the budget accounting drift the
        # serve fleet PR audited against live buffers)
        full = lo == 0 and hi >= self.sf.shape[0]
        sl = (lambda a: a) if full else (lambda a: a[lo:hi])
        tids = jnp.arange(lo, hi, dtype=jnp.int32) % self.k
        return (sl(self.sf), sl(self.tb), sl(self.dl), sl(self.lc),
                sl(self.rc), sl(self.lv), tids,
                None if self.cf is None else sl(self.cf),
                None if self.cm is None else sl(self.cm),
                self.num_bin, self.missing, self.default_bin)


class RawDevicePredictor(_StackedPredictor):
    """Stacked-tree device predictor routing on RAW feature values —
    the device path for boosters with no training dataset attached
    (model-file loads / serving residency)."""

    variant = "raw"

    def __init__(self, models: List, num_features: int,
                 num_tree_per_iteration: int,
                 cat_value_cap: int = RAW_CAT_VALUE_CAP):
        super().__init__()
        self.k = num_tree_per_iteration
        self.num_features = int(num_features)
        T = len(models)
        if T == 0:
            self.ok, self.reason = False, "no_trees"
            return
        if any(getattr(t, "is_linear", False) for t in models):
            self.ok, self.reason = False, "linear_tree"
            return
        N = max(max(t.num_internal for t in models), 1)
        L = max(max(t.num_leaves for t in models), 2)
        has_cat = any(t.cat_threshold for t in models)
        C = 0
        if has_cat:
            # pass 1: highest category value used by any bitset decides
            # the mask width; past the cap it is a degradation reason
            for t in models:
                for i in range(t.num_internal):
                    if not (int(t.decision_type[i]) & 1):
                        continue
                    ci = int(t.threshold[i])
                    words = t.cat_threshold[t.cat_boundaries[ci]:
                                            t.cat_boundaries[ci + 1]]
                    for wi in range(len(words) - 1, -1, -1):
                        w = int(words[wi])
                        if w:
                            C = max(C, wi * 32 + w.bit_length())
                            break
            if C > cat_value_cap:
                self.ok, self.reason = False, "cat_vocab_too_large"
                return
            C = max(C, 1)
            if T * N * C > RAW_CAT_MASK_MAX_ELEMS:
                # the vocab cap bounds C but not T*N*C: a deep many-tree
                # model with a wide vocab would allocate a multi-GB
                # mostly-zero mask — degrade instead
                self.ok, self.reason = False, "cat_mask_too_large"
                return
        depth = 1
        sf = np.zeros((T, N), np.int32)
        th = np.zeros((T, N), np.float32)
        dl = np.zeros((T, N), bool)
        mt = np.zeros((T, N), np.int32)
        lc = np.full((T, N), -1, np.int32)
        rc = np.full((T, N), -1, np.int32)
        lv = np.zeros((T, L), np.float32)
        cf = np.zeros((T, N), bool) if has_cat else None
        cm = np.zeros((T, N, C), bool) if has_cat else None

        for ti, t in enumerate(models):
            ni = t.num_internal
            if ni == 0:
                lv[ti, 0] = t.leaf_value[0]
                continue
            for i in range(ni):
                f = int(t.split_feature[i])
                if f >= self.num_features:
                    self.ok, self.reason = False, "feature_out_of_range"
                    return
                sf[ti, i] = f
                d = int(t.decision_type[i])
                dl[ti, i] = bool(d & 2)
                mt[ti, i] = (d >> 2) & 3
                if d & 1:
                    cf[ti, i] = True
                    ci = int(t.threshold[i])
                    words = t.cat_threshold[t.cat_boundaries[ci]:
                                            t.cat_boundaries[ci + 1]]
                    for wi, w in enumerate(words):
                        w = int(w)
                        while w:
                            bit = (w & -w).bit_length() - 1
                            cm[ti, i, wi * 32 + bit] = True
                            w &= w - 1
            # vectorized per tree; cat nodes' slots hold their (unused)
            # cat_boundaries index, routed via the mask instead
            th[ti, :ni] = threshold_to_f32(np.asarray(t.threshold[:ni]))
            lc[ti, :ni] = t.left_child
            rc[ti, :ni] = t.right_child
            lv[ti, :t.num_leaves] = t.leaf_value
            ld = getattr(t, "leaf_depth", None)
            # model-file trees parse with an all-zero leaf_depth (the
            # text format does not store depth): fall back to the
            # num_internal bound, never to a fake depth of 0
            if ld is not None and len(ld) and int(np.max(ld)) > 0:
                depth = max(depth, int(np.max(ld)))
            else:
                depth = max(depth, ni)

        self.max_steps = _round_up_pow2(depth + 1)
        self.sf = jnp.asarray(sf)
        self.th = jnp.asarray(th)
        self.dl = jnp.asarray(dl)
        self.mt = jnp.asarray(mt)
        self.lc = jnp.asarray(lc)
        self.rc = jnp.asarray(rc)
        self.lv = jnp.asarray(lv)
        self.cf = jnp.asarray(cf) if has_cat else None
        self.cm = jnp.asarray(cm) if has_cat else None
        self._packed = [self.sf, self.th, self.dl, self.mt, self.lc,
                        self.rc, self.lv, self.cf, self.cm]
        self.enc_width = self.num_features
        self.enc_dtype = "float32"
        # widest feature any split actually reads: narrower inputs than
        # the declared feature count are fine as long as they cover it
        # (the host walk accepts them, so the device path must too)
        self.max_split_feature = int(sf.max()) if T else -1

    def encode(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        nf = self.num_features
        if X.shape[1] < nf:
            if X.shape[1] <= self.max_split_feature:
                raise ValueError(
                    f"prediction data has {X.shape[1]} columns but the "
                    f"model splits on feature {self.max_split_feature}")
            # trailing unused features: pad to the canonical width (the
            # pad values route nowhere — no split reads them)
            X = np.concatenate(
                [X, np.zeros((X.shape[0], nf - X.shape[1]), X.dtype)],
                axis=1)
        # trim extra trailing columns (no split can reference them):
        # the encoded operand keeps ONE canonical width per model, so
        # wider inputs cannot fork extra compiled programs
        return np.ascontiguousarray(X[:, :nf], np.float32)

    def run_args(self, lo: int, hi: int) -> Tuple:
        # full-range slice returns the packed arrays themselves (a jnp
        # slice would allocate device copies — see DevicePredictor)
        full = lo == 0 and hi >= self.sf.shape[0]
        sl = (lambda a: a) if full else (lambda a: a[lo:hi])
        tids = jnp.arange(lo, hi, dtype=jnp.int32) % self.k
        return (sl(self.sf), sl(self.th), sl(self.dl), sl(self.mt),
                sl(self.lc), sl(self.rc), sl(self.lv), tids,
                None if self.cf is None else sl(self.cf),
                None if self.cm is None else sl(self.cm))
