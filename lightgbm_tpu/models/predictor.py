"""Batched on-device prediction.

Replaces the reference's per-row host tree walk for batch predict
(ref: predictor.hpp:30 Predictor, gbdt_prediction.cpp — OpenMP over rows,
pointer-chasing per tree) with: host-side binning through the training
BinMappers (exactly the training-time quantization, so routing decisions
are bit-identical to the host walk), then one jit-compiled scan over a
stacked [T, nodes] tree tensor on device — every tree level advances all
rows at once.

Scores accumulate in float32 on device (the host path carries float64;
differences are ~1e-7 relative). The Booster picks this path only for
large batches where throughput dominates; exact-parity flows (model IO
round-trips, SHAP) keep the host walk.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _round_up_pow2(n: int) -> int:
    return 1 << max(1, (n - 1).bit_length())


class DevicePredictor:
    """Stacked-tree device predictor for one Booster state."""

    def __init__(self, models: List, ds, num_tree_per_iteration: int):
        """models: HostTree list; ds: TpuDataset (mappers + used_features)."""
        self.ds = ds
        self.k = num_tree_per_iteration
        self.ok = True
        T = len(models)
        if T == 0:
            self.ok = False
            return
        N = max(max(t.num_internal for t in models), 1)
        L = max(max(t.num_leaves for t in models), 2)
        B = int(max(m.num_bin for m in ds.mappers)) if ds.mappers else 2
        depth = 1
        sf = np.zeros((T, N), np.int32)
        tb = np.zeros((T, N), np.int32)
        dl = np.zeros((T, N), bool)
        lc = np.full((T, N), -1, np.int32)
        rc = np.full((T, N), -1, np.int32)
        lv = np.zeros((T, L), np.float32)
        has_cat = any(t.cat_threshold for t in models)
        cf = np.zeros((T, N), bool) if has_cat else None
        cm = np.zeros((T, N, B), bool) if has_cat else None

        for ti, t in enumerate(models):
            ni = t.num_internal
            if ni == 0:
                lv[ti, 0] = t.leaf_value[0]
                continue
            for i in range(ni):
                real_f = int(t.split_feature[i])
                inner = ds.inner_feature_index(real_f)
                if inner < 0:  # split on a filtered feature: cannot happen
                    self.ok = False  # for self-trained models; bail out
                    return
                sf[ti, i] = inner
                m = ds.mappers[real_f]
                d = int(t.decision_type[i])
                is_cat = bool(d & 1)
                if is_cat:
                    cf[ti, i] = True
                    # value bitset -> bin mask through the category vocab
                    cat_idx = int(t.threshold[i])
                    lo = t.cat_boundaries[cat_idx]
                    hi = t.cat_boundaries[cat_idx + 1]
                    words = t.cat_threshold[lo:hi]
                    for b, cat in enumerate(m.bin_2_categorical):
                        if cat < 0:
                            continue
                        w, bit = divmod(int(cat), 32)
                        if w < len(words) and (words[w] >> bit) & 1:
                            cm[ti, i, b] = True
                else:
                    tb[ti, i] = int(t.threshold_bin[i]) if \
                        len(t.threshold_bin) > i else \
                        int(m.value_to_bin(t.threshold[i]))
                    dl[ti, i] = bool(d & 2)
            lc[ti, :ni] = t.left_child
            rc[ti, :ni] = t.right_child
            lv[ti, :t.num_leaves] = t.leaf_value
            if getattr(t, "leaf_depth", None) is not None \
                    and len(t.leaf_depth):
                depth = max(depth, int(np.max(t.leaf_depth)))
            else:
                depth = max(depth, ni)

        self.max_steps = _round_up_pow2(depth + 1)
        self.sf = jnp.asarray(sf)
        self.tb = jnp.asarray(tb)
        self.dl = jnp.asarray(dl)
        self.lc = jnp.asarray(lc)
        self.rc = jnp.asarray(rc)
        self.lv = jnp.asarray(lv)
        self.cf = jnp.asarray(cf) if has_cat else None
        self.cm = jnp.asarray(cm) if has_cat else None
        F = ds.num_features
        self.num_bin = jnp.asarray(ds.num_bin_per_feat)
        self.missing = jnp.asarray(ds.missing_types)
        self.default_bin = jnp.asarray(
            np.array([ds.mappers[j].default_bin for j in ds.used_features],
                     np.int32))

    # ------------------------------------------------------------------
    def _bin_rows(self, X: np.ndarray) -> np.ndarray:
        ds = self.ds
        out = np.empty((X.shape[0], ds.num_features), np.int32)
        for k, j in enumerate(ds.used_features):
            out[:, k] = ds.mappers[j].value_to_bin(
                np.asarray(X[:, j], np.float64))
        return out

    def predict_raw(self, X: np.ndarray, lo: int, hi: int,
                    chunk_rows: int = 2_000_000) -> np.ndarray:
        """Sum of leaf values of trees [lo, hi) per class, [k, R] float32.

        scipy sparse input is densified PER CHUNK (prediction routes on
        logical bins regardless of the training-side bundle storage)."""
        try:
            import scipy.sparse as sp
            sparse_in = sp.issparse(X)
        except ImportError:  # pragma: no cover
            sparse_in = False
        if sparse_in:
            X = X.tocsr()
            chunk_rows = min(chunk_rows, 262_144)
        n = X.shape[0]
        out = np.zeros((self.k, n), np.float64)
        for c0 in range(0, n, chunk_rows):
            sl = slice(c0, min(n, c0 + chunk_rows))
            Xc = X[sl].toarray() if sparse_in else X[sl]
            bins = jnp.asarray(self._bin_rows(Xc))
            raw = self._predict_chunk(bins, lo, hi)
            out[:, sl] = np.asarray(raw, np.float64)
        return out

    def _make_run(self):
        """Jitted scan over the stacked trees, built ONCE per predictor so
        repeated predict calls hit XLA's compile cache (keyed by shapes)."""
        k = self.k
        num_bin, missing, default_bin = (self.num_bin, self.missing,
                                         self.default_bin)
        max_steps = self.max_steps
        from ..ops.predict import route_rows_to_leaves

        @jax.jit
        def run(bins, sf, tb, dl, lc, rc, lv, tids, cf, cm):
            R = bins.shape[0]

            def tree_step(raw, xs):
                if cf is None:
                    sf_t, tb_t, dl_t, lc_t, rc_t, lv_t, tid = xs
                    cf_t = cm_t = None
                else:
                    (sf_t, tb_t, dl_t, lc_t, rc_t, lv_t, tid, cf_t,
                     cm_t) = xs
                leaves = route_rows_to_leaves(
                    bins, sf_t, tb_t, dl_t, lc_t, rc_t, num_bin,
                    missing, default_bin, max_steps, cf_t, cm_t)
                return raw.at[tid].add(lv_t[leaves]), None

            raw0 = jnp.zeros((k, R), jnp.float32)
            xs = (sf, tb, dl, lc, rc, lv, tids)
            if cf is not None:
                xs = xs + (cf, cm)
            raw, _ = jax.lax.scan(tree_step, raw0, xs)
            return raw
        return run

    def _predict_chunk(self, bins: jax.Array, lo: int, hi: int) -> jax.Array:
        if not hasattr(self, "_run"):
            self._run = self._make_run()
        sel = slice(lo, hi)
        tids = jnp.arange(lo, hi, dtype=jnp.int32) % self.k
        return self._run(bins, self.sf[sel], self.tb[sel], self.dl[sel],
                         self.lc[sel], self.rc[sel], self.lv[sel], tids,
                         None if self.cf is None else self.cf[sel],
                         None if self.cm is None else self.cm[sel])
