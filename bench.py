"""Benchmark: Higgs-shaped GBDT training throughput on one TPU chip.

Mirrors the reference's headline benchmark configuration
(ref: docs/GPU-Performance.rst:108-123 — Higgs, max_bin=63, num_leaves=255,
lr=0.1; docs/Experiments.rst:113 — CPU LightGBM trains Higgs 10.5M×28 in
130.094 s / 500 iterations = 0.2602 s/iter on 2×E5-2690v4).

Drives the full product path (lightgbm_tpu.train -> GBDT driver -> fused
route+histogram Pallas engine) at the REAL 10.5M-row scale by default
(BENCH_ROWS scales down for smoke runs) and prints ONE JSON line:
  {"metric": "higgs_sec_per_iter_10.5M_rows", "value": ..., "unit": "s",
   "vs_baseline": baseline/ours (>1 means faster than reference CPU)}

Engines are tried in order (fused -> frontier -> xla): a kernel that fails
to compile on the attached chip must degrade, not zero the round.

``--micro``: deterministic CPU-backend micro-mode (small synthetic data,
fused engine in interpret mode, dispatch/drain counters from telemetry)
so BENCH_TRAJECTORY gains comparable points even while the chip tunnel
is down — see run_micro().
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

# The bench record must be indestructible (VERDICT r3 weak #1: round 3
# lost an already-measured perf number because the JSON printed only
# after a ~1-hour quality leg that the driver's budget killed).  The
# current best-known result lives here; it is printed+flushed the moment
# each leg lands, and re-emitted by the SIGTERM handler / watchdog if a
# later leg dies, so the LAST stdout line is always parseable JSON.
_RESULT = {"metric": "higgs_sec_per_iter_10.5M_rows", "value": None,
           "unit": "s", "vs_baseline": None, "probe_tfs": None}

# Failure trail: which phases completed + the timer/telemetry snapshot
# collected so far, attached to the record as "tail" whenever a leg dies
# (a `tunnel_stuck_backend_init` must say where the time went, not just
# that it went — ISSUE 3 satellite).
_TAIL = {"phases": []}
_T0 = time.time()

# Persistent bench trajectory: every run appends its (latest) record to
# BENCH_TRAJECTORY.jsonl so scripts/bench_compare.py can diff consecutive
# runs and flag regressions — the bench history must outlive any single
# round's stdout (ISSUE 4 satellite).
_RUN_ID = f"{time.strftime('%Y%m%dT%H%M%S')}_{os.getpid()}"
_TRAJECTORY_PATH = os.environ.get(
    "BENCH_TRAJECTORY",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_TRAJECTORY.jsonl"))


def _append_trajectory():
    """Mirror the current record into the trajectory file, each line
    carrying the timer's phase totals so bench_compare.py can diff
    per-phase, not just the headline number. A run that emits twice
    appends twice — the reader (bench_compare.load_trajectory) keeps
    each run_id's last line, so a plain O(1) append suffices and
    concurrent runs cannot erase each other's records the way a
    read-modify-replace would. Must never kill a run."""
    rec = dict(_RESULT)
    rec["run_id"] = _RUN_ID
    rec["ts"] = round(time.time(), 3)
    try:
        from lightgbm_tpu.utils.timer import global_timer
        rec["phase_timings"] = {
            name: {"total": round(st.total, 4), "count": st.count}
            for name, st in global_timer.stats().items()}
    except Exception:
        pass
    try:
        with open(_TRAJECTORY_PATH, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
    except Exception as e:
        print(f"bench trajectory append failed: {e}", file=sys.stderr)


def _phase(name: str):
    _TAIL["phases"].append({"phase": name, "t": round(time.time() - _T0, 3)})
    print(f"bench phase: {name}", file=sys.stderr)


def _attach_tail():
    try:
        from lightgbm_tpu.utils.timer import global_timer
        _TAIL["timer"] = {name: {"total": round(st.total, 4),
                                 "count": st.count}
                          for name, st in global_timer.stats().items()}
    except Exception as e:  # the tail must never kill the record
        _TAIL["timer_error"] = f"{type(e).__name__}: {e}"[:200]
    _RESULT["tail"] = _TAIL


def _emit():
    print(json.dumps(_RESULT), flush=True)
    _append_trajectory()


def _die_with_record(reason: str):
    _RESULT.setdefault("error", reason)
    _attach_tail()
    _emit()
    os._exit(0)


def _install_guards():
    # SIGTERM: what `timeout` (the driver) sends first
    signal.signal(signal.SIGTERM,
                  lambda s, f: _die_with_record("sigterm"))
    # watchdog thread: fires even when the main thread is stuck inside a
    # blocking device call (signal handlers can't run there)
    deadline = float(os.environ.get("BENCH_DEADLINE_SECS", "3000"))

    def _watch():
        time.sleep(deadline)
        _die_with_record(f"internal_deadline_{deadline:.0f}s")

    threading.Thread(target=_watch, daemon=True).start()


_PROBE_CODE = r"""
import json, time, numpy as np
from lightgbm_tpu.utils.platform import pin_jax_platforms
pin_jax_platforms()
import jax, jax.numpy as jnp
d = jax.devices()
xp = jnp.asarray(np.random.RandomState(1).randn(4096, 4096)
                 .astype(np.float32)).astype(jnp.bfloat16)

@jax.jit
def _chain(m):
    for _ in range(8):
        m = (m @ m) * 1e-3
    return jnp.sum(m.astype(jnp.float32))

float(_chain(xp))
t0 = time.perf_counter()
float(_chain(xp))
tfs = 8 * 2 * 4096 ** 3 / (time.perf_counter() - t0) / 1e12
print(json.dumps({"platform": d[0].platform, "probe_tfs": round(tfs, 1)}))
"""


def _probe_chip(timeout_s: float = None):
    """Backend bring-up + chained-matmul probe in a SUBPROCESS so a stuck
    tunnel (device grant hang: jax.devices() blocks forever, PROFILE.md
    §5) is a recorded reason, not an rc=124 with no JSON. Returns
    (probe_dict, None) on success; (None, reason) only when backend init
    HANGS — a transient probe error is advisory (the caller continues
    without a probe reading, it must not destroy the perf leg)."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "420"))
    last = "probe_failed"
    for attempt in range(2):
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                               capture_output=True, text=True,
                               timeout=timeout_s,
                               cwd=os.path.dirname(os.path.abspath(
                                   __file__)))  # lightgbm_tpu importable
        except subprocess.TimeoutExpired:
            # a hung probe means the main process would hang too —
            # one retry, then bail with the record
            last = "tunnel_stuck_backend_init"
            continue
        if r.returncode != 0:
            if "tunnel_stuck" not in last:   # a stuck-tunnel signal from
                # an earlier attempt must survive: main() bails on it
                # instead of walking into the same hang in-process
                last = f"probe_failed: {r.stderr[-200:]}"
            continue
        try:
            return json.loads(r.stdout.strip().splitlines()[-1]), None
        except Exception:
            last = f"probe_unparseable: {r.stdout[-200:]}"
    return None, last


def _free_port() -> int:
    # the launcher's probe (SO_REUSEADDR narrows the rebind race);
    # imported lazily — by the time a bench leg needs a port,
    # lightgbm_tpu is imported anyway
    from lightgbm_tpu.parallel.launcher import _free_port as probe
    return probe()


def _make_data(n_rows: int, n_feat: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n_rows, n_feat).astype(np.float32)
    w = rng.randn(n_feat).astype(np.float32)
    y = (X @ w + 0.5 * rng.randn(n_rows) > 0).astype(np.float32)
    return X, y


def _run(engine: str, X, y, n_iters: int):
    import jax
    import lightgbm_tpu as lgb
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 1,
              "min_sum_hessian_in_leaf": 1e-3, "verbose": -1,
              "metric": "None", "tpu_engine": engine}
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    booster = lgb.Booster(params=params, train_set=ds)
    g = booster._gbdt

    def settle():
        # the driver pipelines iterations asynchronously; timing is only
        # honest if the host model list AND the device queue are settled
        if hasattr(g, "drain_pending"):
            g.drain_pending()
        jax.block_until_ready(g.scores)

    booster.update()  # warmup: compile + first tree
    booster.update()  # second iter compiles the epilogue CONT step
    settle()
    t0 = time.perf_counter()
    for _ in range(n_iters):
        booster.update()
    settle()
    return (time.perf_counter() - t0) / n_iters


def _quality_leg(engine: str, iters: int = 500) -> dict:
    """Differential AUC vs the rebuilt reference CPU package on identical
    data + params (VERDICT r2 #4: the bf16 hi/lo histogram precision claim
    needs a quality number at scale, not a 0.005-tolerance fixture).
    Ref contract being matched: docs/GPU-Performance.rst:136 — the fp32-
    histogram GPU build holds AUC to ~5e-4 of the CPU build on Higgs.
    Our AUC is pushed into _RESULT and emitted BEFORE the (up to 1 h)
    reference-CPU subprocess so a deadline mid-reference-run cannot
    destroy the measured TPU number."""
    import lightgbm_tpu as lgb
    from sklearn.metrics import roc_auc_score

    n_train = int(os.environ.get("BENCH_QUALITY_ROWS", 1_000_000))
    n_test = max(100_000, n_train // 5)
    rng = np.random.RandomState(7)
    n_feat = 28
    X = rng.rand(n_train + n_test, n_feat).astype(np.float32)
    w = rng.randn(n_feat).astype(np.float32)
    # interactions make the trees matter; noise keeps AUC off the ceiling
    margin = X @ w + 0.9 * X[:, 0] * X[:, 1] - 0.9 * X[:, 2] * X[:, 3]
    y = (margin + 0.8 * rng.randn(len(X)) > np.median(margin)) \
        .astype(np.float32)
    Xtr, ytr = X[:n_train], y[:n_train]
    Xte, yte = X[n_train:], y[n_train:]
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 255,
              "learning_rate": 0.1, "num_iterations": iters,
              "verbose": -1, "metric": "None"}

    ds = lgb.Dataset(Xtr, label=ytr, params={"max_bin": 63, "verbose": -1})
    bst = None
    # the quality claim is about the fused engine's bf16 hi/lo histograms
    # — prefer it even when the perf leg degraded to another engine
    for eng in dict.fromkeys(["fused", engine, "xla"]):
        for attempt in range(2):
            try:
                bst = lgb.train(dict(params, tpu_engine=eng), ds)
                break
            except Exception as e:
                print(f"quality engine {eng} attempt {attempt} failed: "
                      f"{str(e)[:200]}", file=sys.stderr)
        if bst is not None:
            break
    if bst is None:
        raise RuntimeError("quality leg: every engine failed to train")
    auc = float(roc_auc_score(yte, bst.predict(Xte)))
    out = {"auc": round(auc, 6),
           "auc_bayes": round(float(roc_auc_score(yte, margin[n_train:])),
                              6)}
    _RESULT.update(out)
    _emit()   # the measured TPU AUC is now on stdout, whatever happens
              # to the reference-CPU leg below

    # the reference package is built out-of-tree by
    # scripts/build_reference.sh; absent -> report our AUC alone
    if os.path.isdir("/tmp/refpkg"):
        import subprocess
        code = (
            "import sys, json, numpy as np\n"
            "sys.path.insert(0, '/tmp/refpkg')\n"
            "import lightgbm as rl\n"
            "from sklearn.metrics import roc_auc_score\n"
            f"d = np.load('/tmp/bench_quality.npz')\n"
            f"ds = rl.Dataset(d['Xtr'], label=d['ytr'],\n"
            f"                params={{'max_bin': 63, 'verbose': -1}})\n"
            f"b = rl.train({params!r}, ds)\n"
            "auc = roc_auc_score(d['yte'], b.predict(d['Xte']))\n"
            "print(json.dumps({'auc_ref': round(float(auc), 6)}))\n")
        np.savez("/tmp/bench_quality.npz", Xtr=Xtr, ytr=ytr, Xte=Xte,
                 yte=yte)
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=3600)
            ref = json.loads(r.stdout.strip().splitlines()[-1])
            out.update(ref)
            out["auc_delta"] = round(out["auc"] - ref["auc_ref"], 6)
        except Exception as e:
            print(f"quality leg: reference run failed: {e}",
                  file=sys.stderr)
    return out


def run_micro() -> None:
    """Deterministic CPU-backend micro benchmark (``--micro``).

    The chip tunnel's availability swings can leave whole rounds with
    ``value: null, error: tunnel_*`` — this mode gives BENCH_TRAJECTORY
    real, comparable points regardless: a small synthetic dataset on the
    CPU backend through the REAL product path (lgb.train -> megastep/
    pipelined fast path, fused engine in interpret mode), with the
    dispatch-per-iteration and drain counters pulled from telemetry so
    bench_compare.py can flag a fast-path eviction (dispatch-count
    regression) even where wall-clock noise would hide it."""
    os.environ["JAX_PLATFORMS"] = "cpu"   # before any jax import
    _RESULT.update(metric="micro_cpu_sec_per_iter", unit="s")
    _install_guards()
    from lightgbm_tpu.utils.timer import global_timer
    global_timer.enable()
    _phase("micro_start")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_CACHE_DIR",
                                     "/tmp/lgbm_tpu_jax_cache_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import lightgbm_tpu as lgb

    n_rows = int(os.environ.get("BENCH_MICRO_ROWS", 4000))
    n_iters = int(os.environ.get("BENCH_MICRO_ITERS", 8))
    n_feat = 10
    _RESULT["bench_config"] = {"mode": "micro", "rows": n_rows,
                               "iters": n_iters,
                               "eval_iters": int(os.environ.get(
                                   "BENCH_MICRO_EVAL_ITERS", 16))}
    _RESULT["platform"] = "cpu"
    X, y = _make_data(n_rows, n_feat)

    tel_path = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                            f"bench_micro_tel_{os.getpid()}.jsonl")
    # run reports land at stable paths so CI can run scripts/run_diff.py
    # over the job's two reports after the bench exits
    report_dir = os.environ.get("BENCH_REPORT_DIR",
                                os.environ.get("TMPDIR", "/tmp"))
    report_base = os.path.join(report_dir, "bench_micro_run_report.json")
    report_obs = os.path.join(report_dir,
                              "bench_micro_run_report_obs.json")
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 15,
              "learning_rate": 0.2, "min_data_in_leaf": 5, "verbose": -1,
              "metric": "None", "tpu_engine": "fused",
              # explicit: interpret-mode megastep is opt-in (the micro
              # mode exists precisely to measure its dispatch counters)
              "tpu_megastep": True, "telemetry_out": tel_path}
    t0 = time.perf_counter()
    bst = lgb.train(dict(params, run_report_out=report_base), lgb.Dataset(
        X, label=y, params={"max_bin": 63, "verbose": -1}),
        num_boost_round=n_iters)
    wall = time.perf_counter() - t0
    _phase("micro_train_ok")
    snap = bst.telemetry()
    c = snap.get("counters", {})
    # the KEPT iteration count is the denominator everywhere: a run that
    # dries up early (no-more-splits) must not understate sec/iter
    iters = max(1, int(c.get("iterations", n_iters)))
    _RESULT["value"] = round(wall / iters, 5)
    _RESULT["iterations_kept"] = iters
    _RESULT["engine"] = "fused"
    _RESULT["counters"] = {k: v for k, v in sorted(c.items())
                           if k.startswith(("train.", "iterations",
                                            "events."))}
    _RESULT["dispatches_per_iter"] = round(
        float(c.get("train.dispatches", 0)) / iters, 4)
    _RESULT["drains"] = int(c.get("train.drains", 0))
    _RESULT["fast_path"] = bool(bst._gbdt._fast_path_ok())
    # attach the consolidated run report (trimmed to its comparable
    # core — the full artifact stays on disk for run_diff) so the
    # trajectory history carries attribution, not just headlines
    try:
        rep = json.load(open(report_base))
        _RESULT["run_report"] = {
            "path": report_base, "schema": rep.get("schema"),
            "run_id": rep.get("run_id"),
            "derived": rep.get("derived"),
            "cost": {k: rep.get("cost", {}).get(k)
                     for k in ("flops_per_iter", "hlo_bytes_per_iter",
                               "achieved_fraction")},
            "reasons": rep.get("reasons")}
        _RESULT["run_report_ok"] = bool(
            str(rep.get("schema", "")).startswith(
                "lightgbm_tpu.run_report/"))
    except Exception as e:
        print(f"run report attach failed: {e}", file=sys.stderr)
        _RESULT["run_report_ok"] = False
    _emit()   # the bare-training counters are on stdout now

    # ---- eval leg: the dominant production config — train() with two
    # valid sets + early_stopping + log_evaluation + record_evaluation —
    # must stay on the megastep (on-device eval + drain-replay
    # callbacks, metric/traced.py). `eval_dispatches_per_iter` is the
    # deterministic gate: a regression back to the per-iteration sync
    # driver moves it from ~1/chunk to >= 3.
    from lightgbm_tpu import callback as lgb_cb
    tel_eval = tel_path + ".eval"
    n_eval_iters = int(os.environ.get("BENCH_MICRO_EVAL_ITERS", 16))
    Xv1, yv1 = _make_data(max(512, n_rows // 4), n_feat, seed=1)
    Xv2, yv2 = _make_data(max(512, n_rows // 4), n_feat, seed=2)
    rec = {}
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    t0 = time.perf_counter()
    bst2 = lgb.train(
        dict(params, telemetry_out=tel_eval,
             metric=["binary_logloss", "auc"], early_stopping_round=25),
        ds, num_boost_round=n_eval_iters,
        valid_sets=[lgb.Dataset(Xv1, label=yv1, reference=ds),
                    lgb.Dataset(Xv2, label=yv2, reference=ds)],
        callbacks=[lgb_cb.log_evaluation(100),
                   lgb_cb.record_evaluation(rec)])
    eval_wall = time.perf_counter() - t0
    _phase("micro_eval_train_ok")
    c2 = bst2.telemetry().get("counters", {})
    eval_iters = max(1, int(c2.get("iterations", n_eval_iters)))
    _RESULT["eval_sec_per_iter"] = round(eval_wall / eval_iters, 5)
    _RESULT["eval_dispatches_per_iter"] = round(
        float(c2.get("train.dispatches", 0)) / eval_iters, 4)
    _RESULT["eval_iterations_kept"] = eval_iters
    _RESULT["eval_curve_points"] = len(
        rec.get("valid_0", {}).get("binary_logloss", []))
    # the bare-leg `counters`/`fast_path`/`drains` fields above describe
    # the FIRST training; the eval leg's counters get their own
    # namespaced copy so the merged record stays unambiguous
    _RESULT["eval_counters"] = {k: v for k, v in sorted(c2.items())
                                if k.startswith(("train.", "iterations",
                                                 "events."))}
    _emit()   # the eval-leg counters are on stdout now

    # ---- checkpoint leg: the bare training again with async resilience
    # checkpoints armed. Checkpoints capture at drain boundaries on a
    # background thread, so they must be dispatch-neutral:
    # ckpt_dispatches_per_iter == dispatches_per_iter EXACTLY is the
    # deterministic gate (bench_compare + the perf-smoke absolute
    # assertion) — any regression that makes checkpointing evict the
    # fast path or add device round trips moves the counter.
    import shutil
    import tempfile
    ckpt_root = tempfile.mkdtemp(prefix="bench_micro_ckpt_")
    tel_ckpt = tel_path + ".ckpt"
    ds3 = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    t0 = time.perf_counter()
    bst3 = lgb.train(dict(params, telemetry_out=tel_ckpt,
                          checkpoint_dir=ckpt_root, checkpoint_period=4),
                     ds3, num_boost_round=n_iters)
    ckpt_wall = time.perf_counter() - t0
    _phase("micro_ckpt_train_ok")
    c3 = bst3.telemetry().get("counters", {})
    ckpt_iters = max(1, int(c3.get("iterations", n_iters)))
    _RESULT["ckpt_sec_per_iter"] = round(ckpt_wall / ckpt_iters, 5)
    _RESULT["ckpt_dispatches_per_iter"] = round(
        float(c3.get("train.dispatches", 0)) / ckpt_iters, 4)
    _RESULT["checkpoints_written"] = int(c3.get("ckpt.written", 0))
    shutil.rmtree(ckpt_root, ignore_errors=True)

    # ---- observability leg: the bare training again with the LIVE
    # OpenMetrics exporter serving scrapes. The observability plane may
    # not touch the fast path: obs_dispatches_per_iter must equal
    # dispatches_per_iter EXACTLY (bench_compare deterministic counter +
    # the perf-smoke absolute assertion), and a mid-process scrape of
    # the endpoint must return parseable OpenMetrics whose dispatch
    # counter agrees with the registry snapshot.
    obs_port = _free_port()
    tel_obs = tel_path + ".obs"
    ds4 = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    t0 = time.perf_counter()
    bst4 = lgb.train(dict(params, telemetry_out=tel_obs,
                          metrics_port=obs_port,
                          run_report_out=report_obs),
                     ds4, num_boost_round=n_iters)
    obs_wall = time.perf_counter() - t0
    _phase("micro_obs_train_ok")
    c4 = bst4.telemetry().get("counters", {})
    obs_iters = max(1, int(c4.get("iterations", n_iters)))
    _RESULT["obs_sec_per_iter"] = round(obs_wall / obs_iters, 5)
    _RESULT["obs_dispatches_per_iter"] = round(
        float(c4.get("train.dispatches", 0)) / obs_iters, 4)
    # the exporter outlives finalize by design — the endpoint must
    # answer while the process holds the booster. Scrape its ACTUAL
    # url: a TCP race on the probed port degrades the exporter to an
    # ephemeral bind (its own resilience contract), not a CI failure.
    mx = getattr(bst4._gbdt, "_metrics", None)
    try:
        from lightgbm_tpu.obs.export import scrape
        _, body = scrape(mx.url, timeout=10)
        line = next(l for l in body.splitlines()
                    if l.startswith("lgbm_train_dispatches_total"))
        _RESULT["exporter_scrape_ok"] = (
            float(line.rsplit(" ", 1)[1])
            == float(c4.get("train.dispatches", 0)))
    except Exception as e:
        print(f"exporter scrape failed: {e}", file=sys.stderr)
        _RESULT["exporter_scrape_ok"] = False
    # armed-but-untriggered /profile endpoint: arming after the last
    # drain boundary leaves the request pending forever — the window
    # never opens, the counters never move (the CI assertion that
    # obs_dispatches_per_iter == dispatches_per_iter above is measured
    # with this armed endpoint live), and a second POST refuses with
    # 409 (the overlap contract)
    try:
        from lightgbm_tpu.obs.export import post
        base_url = mx.url.rsplit("/metrics", 1)[0]
        code1, body1 = post(f"{base_url}/profile?iters=2")
        code2, body2 = post(f"{base_url}/profile?iters=2")
        _RESULT["profile_armed_untriggered_ok"] = (
            code1 == 200 and bool(body1.get("armed"))
            and code2 == 409 and not body2.get("armed", True))
        c4b = bst4.telemetry().get("counters", {})
        # arming must not have moved a single dispatch
        _RESULT["profile_armed_untriggered_ok"] &= (
            c4b.get("train.dispatches") == c4.get("train.dispatches"))
    except Exception as e:
        print(f"profile arm check failed: {e}", file=sys.stderr)
        _RESULT["profile_armed_untriggered_ok"] = False
    finally:
        if mx is not None:
            mx.stop()
    _emit()   # the obs-leg counters are on stdout now

    # ---- control-plane leg: POST /profile?iters=2 against a LIVE
    # megastep training job (the ISSUE 15 acceptance run). Two chunks
    # of n_iters iterations each, a watcher thread arming the endpoint
    # as soon as it answers: the on-demand jax.profiler window opens at
    # a drain boundary / iteration edge and closes at the next drain
    # boundary — so the leg must measure ctl_dispatches_per_iter ==
    # dispatches_per_iter EXACTLY (2 dispatches / 2*n_iters iterations
    # == 1/n_iters == the base leg; profiling is dispatch-neutral),
    # with exactly one closed profile_window and a non-empty trace dir.
    import threading as _threading
    ctl_port = _free_port()
    tel_ctl = tel_path + ".ctl"
    ctl_prof_dir = tempfile.mkdtemp(prefix="bench_micro_ctlprof_")
    # roofline leg rides the control-plane leg: the window close parses
    # the trace (obs/kernelstats.py) and appends measured samples to
    # this perf database (obs/perfdb.py); a second profiled run below
    # appends to the SAME file to prove cross-run accumulation
    ctl_perfdb = tel_path + ".perfdb"
    if os.path.exists(ctl_perfdb):
        os.unlink(ctl_perfdb)
    n_ctl_iters = 2 * n_iters
    ctl_stop = _threading.Event()
    ctl_armed = {}

    def _arm_profile():
        from lightgbm_tpu.obs.export import post as _post
        from lightgbm_tpu.obs.export import scrape as _scrape
        url = (f"http://127.0.0.1:{ctl_port}/profile?iters=2"
               f"&dir={ctl_prof_dir}")
        # wait until the first chunk has DISPATCHED before arming, so
        # the window's open lands at the chunk's drain boundary — the
        # drain-boundary semantics the acceptance criterion names
        # (arming earlier is equally dispatch-neutral, just opens at
        # the iteration-0 edge instead). Poll /snapshot, NOT /metrics:
        # the metrics body is TTL-cached ~1 s, and a stale read here
        # could slip the arm past the first drain boundary on a fast
        # runner (the window must close at a drain, not at finalize)
        while not ctl_stop.is_set():
            try:
                _, body = _scrape(
                    f"http://127.0.0.1:{ctl_port}/snapshot", timeout=2)
                if json.loads(body).get("counters", {}).get(
                        "train.dispatches", 0) >= 1:
                    break
            except Exception:
                pass
            time.sleep(0.02)
        while not ctl_stop.is_set():
            try:
                code, body = _post(url, timeout=2)
                ctl_armed["code"], ctl_armed["body"] = code, body
                if code == 200:
                    return
            except Exception:
                pass
            time.sleep(0.02)

    ctl_thread = _threading.Thread(target=_arm_profile, daemon=True)
    ctl_thread.start()
    ds6 = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    t0 = time.perf_counter()
    bst6 = lgb.train(dict(params, telemetry_out=tel_ctl,
                          metrics_port=ctl_port,
                          tpu_megastep_iters=n_iters,
                          perf_db=ctl_perfdb),
                     ds6, num_boost_round=n_ctl_iters)
    ctl_wall = time.perf_counter() - t0
    ctl_stop.set()
    ctl_thread.join(timeout=5)
    _phase("micro_ctl_train_ok")
    snap6 = bst6._gbdt.telemetry.snapshot()
    c6 = snap6.get("counters", {})
    ctl_iters = max(1, int(c6.get("iterations", n_ctl_iters)))
    _RESULT["ctl_sec_per_iter"] = round(ctl_wall / ctl_iters, 5)
    _RESULT["ctl_dispatches_per_iter"] = round(
        float(c6.get("train.dispatches", 0)) / ctl_iters, 4)
    windows = [e for e in snap6.get("events", [])
               if e.get("event") == "profile_window"]
    _RESULT["ctl_profile_windows"] = sum(
        1 for e in windows if e.get("state") == "closed")
    _RESULT["ctl_profile_states"] = [e.get("state") for e in windows]
    ctl_files = [os.path.join(r, f)
                 for r, _, fs in os.walk(ctl_prof_dir) for f in fs]
    _RESULT["ctl_profile_trace_ok"] = bool(ctl_files)
    # ---- roofline leg (rides the control-plane leg): the window close
    # above already parsed the trace via obs/kernelstats.py and joined
    # it to the cost ledger. Deterministic gates: join coverage must be
    # EXACTLY 1.0 (every measured megastep dispatch joined its analytic
    # cost signature) and the dispatch counter measured WITH the parse
    # active must equal the base leg's (the parser is host-side work at
    # a window close the driver already owns — dispatch-neutral).
    g6 = snap6.get("gauges", {})
    _RESULT["roofline_join_coverage"] = float(
        g6.get("roofline.join_coverage", -1.0))
    _RESULT["roofline_joined_executables"] = int(
        g6.get("roofline.joined_executables", 0))
    _RESULT["roofline_dispatches_per_iter"] = round(
        float(c6.get("train.dispatches", 0)) / ctl_iters, 4)
    _RESULT["roofline_trace_bytes_ok"] = bool(
        g6.get("profile.trace_bytes", 0) > 0
        and g6.get("profile.trace_files", 0) > 0)
    mx6 = getattr(bst6._gbdt, "_metrics", None)
    if mx6 is not None:
        mx6.stop()
    shutil.rmtree(ctl_prof_dir, ignore_errors=True)
    # second profiled run, same shape, appending to the SAME perf
    # database — this one through the profile_dir config window (the
    # other capture flavor; it closes at finalize) — then assert the
    # shape key accumulated one sample per run. perfdb_samples == 2 is
    # the deterministic cross-run-accumulation gate.
    ctl2_prof_dir = tempfile.mkdtemp(prefix="bench_micro_ctlprof2_")
    ds7 = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    bst7 = lgb.train(dict(params, telemetry_out=tel_path + ".ctl2",
                          tpu_megastep_iters=n_iters,
                          profile_dir=ctl2_prof_dir,
                          perf_db=ctl_perfdb),
                     ds7, num_boost_round=n_ctl_iters)
    _phase("micro_ctl2_train_ok")
    from lightgbm_tpu.obs import perfdb as _perfdb
    _db = _perfdb.PerfDB(ctl_perfdb).load()
    _summ = _perfdb.summarize(_db["rows"])
    _RESULT["perfdb_rows"] = len(_db["rows"])
    _RESULT["perfdb_keys"] = len(_summ)
    # samples accumulated for the most-sampled shape key (the megastep
    # executable both runs measured): exactly one per profiled run
    _RESULT["perfdb_samples"] = max(
        (e["samples"] for e in _summ), default=0)
    mx7 = getattr(bst7._gbdt, "_metrics", None)
    if mx7 is not None:
        mx7.stop()
    shutil.rmtree(ctl2_prof_dir, ignore_errors=True)
    _emit()   # the control-plane + roofline counters are on stdout now

    # ---- histogram-plane leg: quantized gradients + gain screening +
    # adaptive per-feature bins (ROADMAP item 4). Two trainings on a
    # MIXED-CARDINALITY dataset (half the features carry 8 distinct
    # values — the shape adaptive bins exist for): an f32 full-plane
    # baseline and the three-cut configuration. Deterministic gates:
    # `hist_dispatches_per_iter` == dispatches_per_iter EXACTLY (the
    # cuts ride the megastep, never evict it), `hist_bytes_per_iter`
    # (the driver's analytic byte model of what the histogram kernels
    # read/build/keep per iteration — layout arithmetic, zero noise)
    # must show >= 2x reduction vs `hist_bytes_per_iter_f32`, plus
    # `hist_quant_bits` and `screening_active_features`.
    n_hf = 12
    rng_h = np.random.RandomState(5)
    Xh = rng_h.rand(n_rows, n_hf).astype(np.float32)
    Xh[:, n_hf // 2:] = np.floor(Xh[:, n_hf // 2:] * 8.0) / 8.0
    yh = (Xh @ rng_h.randn(n_hf).astype(np.float32) > 0) \
        .astype(np.float32)
    tel_hb = tel_path + ".histbase"
    dsh = lgb.Dataset(Xh, label=yh, params={"max_bin": 63, "verbose": -1})
    bsth0 = lgb.train(dict(params, telemetry_out=tel_hb), dsh,
                      num_boost_round=n_iters)
    gh0 = bsth0.telemetry().get("gauges", {})
    _RESULT["hist_bytes_per_iter_f32"] = float(
        gh0.get("hist.bytes_per_iter", 0.0))
    tel_hc = tel_path + ".histcut"
    cut_params = dict(params, telemetry_out=tel_hc,
                      tpu_quantized_grad=16, tpu_gain_screening=True,
                      tpu_screening_warmup=2,
                      tpu_screening_explore_period=4,
                      tpu_adaptive_bins=True)
    dsh2 = lgb.Dataset(Xh, label=yh, params={"max_bin": 63, "verbose": -1})
    t0 = time.perf_counter()
    bsth = lgb.train(cut_params, dsh2, num_boost_round=n_iters)
    hist_wall = time.perf_counter() - t0
    _phase("micro_hist_train_ok")
    snap_h = bsth.telemetry()
    ch = snap_h.get("counters", {})
    gh = snap_h.get("gauges", {})
    hist_iters = max(1, int(ch.get("iterations", n_iters)))
    _RESULT["hist_sec_per_iter"] = round(hist_wall / hist_iters, 5)
    _RESULT["hist_dispatches_per_iter"] = round(
        float(ch.get("train.dispatches", 0)) / hist_iters, 4)
    _RESULT["hist_bytes_per_iter"] = float(
        gh.get("hist.bytes_per_iter", 0.0))
    _RESULT["hist_quant_bits"] = float(gh.get("hist.quant_bits", 0.0))
    _RESULT["screening_active_features"] = float(
        gh.get("screening.active_features", 0.0))
    _RESULT["hist_bytes_ratio"] = round(
        _RESULT["hist_bytes_per_iter_f32"]
        / max(1.0, _RESULT["hist_bytes_per_iter"]), 4)
    _emit()   # the histogram-plane counters are on stdout now

    # ---- ingest leg: chunked streaming ingest + binary dataset cache
    # (lightgbm_tpu/ingest/). Deterministic gates: `ingest_chunks`
    # (two streaming passes x ceil(rows/chunk)),
    # `ingest_max_live_chunks` <= 2 (the bounded-host-RSS invariant),
    # `ingest_model_mismatch` == 0 (streamed/cached model byte-equal to
    # the monolithic text load), and `ingest_dispatches_per_iter` ==
    # dispatches_per_iter EXACTLY (ingest is a data-loading plane — it
    # must not touch the training fast path). Timing-informational:
    # `prefetch_host_wait_ms` and `cache_hit_startup_ratio` (cold text
    # parse+bin construct time / cache-hit mmap construct time).
    ingest_dir = tempfile.mkdtemp(prefix="bench_micro_ingest_")
    csv_path = os.path.join(ingest_dir, "train.csv")
    with open(csv_path, "w") as fh:
        for i in range(n_rows):
            fh.write(",".join([f"{y[i]:g}"]
                              + [repr(float(v)) for v in X[i]]) + "\n")
    chunk = max(1, n_rows // 4)
    mono_ds_params = {"max_bin": 63, "verbose": -1}
    stream_ds_params = dict(mono_ds_params, two_round=True,
                            ingest_chunk_rows=chunk, save_binary=True)
    plain_params = {k: v for k, v in params.items()
                    if k != "telemetry_out"}
    t0 = time.perf_counter()
    ds_text = lgb.Dataset(csv_path, params=dict(mono_ds_params))
    ds_text.construct()
    text_construct_s = time.perf_counter() - t0
    m_text = lgb.train(dict(plain_params), ds_text,
                       num_boost_round=n_iters)

    tel_ing = tel_path + ".ingest"
    t0 = time.perf_counter()
    # pre-construct like the monolithic leg above so the sidecar cache
    # fingerprint is computed from the DATASET params alone (a booster
    # param merged pre-construction would change the digest and turn
    # the cache-hit leg below into a rebuild)
    ds_stream = lgb.Dataset(csv_path, params=dict(stream_ds_params))
    ds_stream.construct()
    bst5 = lgb.train(dict(params, telemetry_out=tel_ing), ds_stream,
                     num_boost_round=n_iters)
    ing_wall = time.perf_counter() - t0
    _phase("micro_ingest_train_ok")
    snap5 = bst5.telemetry()
    c5 = snap5.get("counters", {})
    g5 = snap5.get("gauges", {})
    ing_iters = max(1, int(c5.get("iterations", n_iters)))
    _RESULT["ingest_sec_per_iter"] = round(ing_wall / ing_iters, 5)
    _RESULT["ingest_dispatches_per_iter"] = round(
        float(c5.get("train.dispatches", 0)) / ing_iters, 4)
    _RESULT["ingest_chunks"] = int(c5.get("ingest.chunks", 0))
    _RESULT["ingest_rows"] = int(c5.get("ingest.rows", 0))
    _RESULT["ingest_max_live_chunks"] = int(
        g5.get("ingest.max_live_chunks", 0))
    _RESULT["prefetch_chunks"] = int(c5.get("prefetch.chunks", 0))
    _RESULT["prefetch_host_wait_ms"] = round(
        float(c5.get("prefetch.host_wait_ms", 0.0)), 3)

    # cache-hit startup: the streamed run above wrote the sidecar
    # cache; this construct must mmap it (no parsing, no binning)
    t0 = time.perf_counter()
    ds_hit = lgb.Dataset(csv_path, params=dict(stream_ds_params))
    ds_hit.construct()
    cache_construct_s = time.perf_counter() - t0
    stats_hit = ds_hit._inner.ingest_stats or {}
    _RESULT["ingest_cache_hit"] = int(stats_hit.get("cache_hit", 0))
    _RESULT["cache_hit_startup_ratio"] = round(
        text_construct_s / max(cache_construct_s, 1e-9), 3)
    m_hit = lgb.train(dict(plain_params), ds_hit,
                      num_boost_round=n_iters)
    _RESULT["ingest_model_mismatch"] = float(
        m_text.model_to_string(num_iteration=-1)
        != m_hit.model_to_string(num_iteration=-1))
    shutil.rmtree(ingest_dir, ignore_errors=True)
    _emit()   # the ingest-leg counters are on stdout now

    # ---- drift leg: the drift & lineage plane (obs/drift.py). The
    # training-side profile capture is pure host numpy at dataset
    # finalize, so `drift_dispatches_per_iter` must EQUAL
    # dispatches_per_iter EXACTLY, with the profile + provenance
    # blocks embedded in the artifact. The serving-side DriftMonitor
    # accumulates on the already-encoded batch host-side, so the
    # closed loop keeps `serve_drift_dispatches_per_request` at
    # exactly 1.0 with zero compiles — while a deterministically
    # shifted feed (np.clip(x + 0.35, 0, 1) vs the rand(0,1) training
    # distribution) raises EXACTLY one hysteresis-gated drift_alert at
    # a reproducible PSI, and the in-distribution control raises none.
    from lightgbm_tpu.serve import PredictionService as _DriftSvc
    tel_drift = tel_path + ".drift"
    ds_dr = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    t0 = time.perf_counter()
    bst_dr = lgb.train(dict(params, telemetry_out=tel_drift,
                            drift_profile=True), ds_dr,
                       num_boost_round=n_iters)
    drift_wall = time.perf_counter() - t0
    _phase("micro_drift_train_ok")
    c7 = bst_dr.telemetry().get("counters", {})
    dr_iters = max(1, int(c7.get("iterations", n_iters)))
    _RESULT["drift_sec_per_iter"] = round(drift_wall / dr_iters, 5)
    _RESULT["drift_dispatches_per_iter"] = round(
        float(c7.get("train.dispatches", 0)) / dr_iters, 4)
    model_dr = bst_dr.model_to_string()
    _RESULT["drift_profile_embedded"] = float(
        "\ndata_profile:\n" in model_dr and "\nprovenance:\n" in model_dr)

    def _drift_serve(shift):
        svc = _DriftSvc({"m": bst_dr}, max_batch_rows=256,
                        max_delay_ms=0.5, min_bucket_rows=16,
                        batch_events=False, drift_eval_rows=128,
                        drift_hysteresis=2)
        svc.warmup()
        rng_d = np.random.RandomState(17)
        s0 = svc.stats()
        for _ in range(20):
            Xq = rng_d.rand(40, n_feat).astype(np.float32)
            if shift:
                Xq = np.clip(Xq + 0.35, 0.0, 1.0).astype(np.float32)
            svc.predict("m", Xq, timeout=60)
        s1 = svc.stats()
        # close() joins the batcher worker, and post-batch drift_flush
        # records run synchronously on it — snapshotting after close
        # makes the final evaluation (and so psi_max) deterministic
        svc.close()
        snap_d = svc.tel.snapshot()
        return {
            "dpr": round((s1["dispatches"] - s0["dispatches"]) / 20.0, 6),
            "cp1k": round((s1["compiles"] - s0["compiles"]) * 50.0, 6),
            "alerts": int(snap_d.get("counters", {})
                          .get("drift.alerts", 0)),
            "psi_max": round(float(snap_d.get("gauges", {})
                                   .get("drift.psi_max", 0.0)), 4)}

    ctrl = _drift_serve(shift=False)
    drifted = _drift_serve(shift=True)
    _phase("micro_drift_serve_ok")
    _RESULT["serve_drift_dispatches_per_request"] = drifted["dpr"]
    _RESULT["serve_drift_compiles_per_1k"] = drifted["cp1k"]
    _RESULT["drift_alerts"] = drifted["alerts"]
    _RESULT["drift_psi_max"] = drifted["psi_max"]
    _RESULT["drift_alerts_control"] = ctrl["alerts"]
    _RESULT["drift_psi_max_control"] = ctrl["psi_max"]
    _emit()   # the drift-plane counters are on stdout now

    # ---- slo leg: the SLO plane (obs/slo.py) armed with the BUILT-IN
    # objective catalog on a clean training run. The engine evaluates
    # host-side telemetry snapshots on its daemon ticker plus the drain
    # boundaries the driver already owns, so arming it is
    # dispatch-neutral: slo_dispatches_per_iter must EQUAL
    # dispatches_per_iter EXACTLY (bench_compare deterministic counter
    # + the perf-smoke absolute assertion). The finalize force-tick
    # makes slo_ticks >= 1 deterministic, and a healthy run must
    # produce ZERO alerts — slo_alerts is the false-positive gate.
    tel_slo = tel_path + ".slo"
    ds_slo = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    t0 = time.perf_counter()
    bst_slo = lgb.train(dict(params, telemetry_out=tel_slo,
                             slo_enabled=True),
                        ds_slo, num_boost_round=n_iters)
    slo_wall = time.perf_counter() - t0
    _phase("micro_slo_train_ok")
    c8 = bst_slo.telemetry().get("counters", {})
    slo_iters = max(1, int(c8.get("iterations", n_iters)))
    _RESULT["slo_sec_per_iter"] = round(slo_wall / slo_iters, 5)
    _RESULT["slo_dispatches_per_iter"] = round(
        float(c8.get("train.dispatches", 0)) / slo_iters, 4)
    _RESULT["slo_ticks"] = int(c8.get("slo.ticks", 0))
    _RESULT["slo_alerts"] = int(c8.get("slo.alerts_fired", 0))
    _emit()   # the slo-plane counters are on stdout now

    # ---- multiproc leg: 2 REAL processes x 2 virtual CPU devices over
    # one gloo mesh, tree_learner=data on the fused engine with the
    # megastep armed — the pod-scale fast path. The deterministic gate
    # is the ABSOLUTE parity contract `mp_dispatches_per_iter ==
    # dispatches_per_iter` (0.125 at defaults): the multi-chip megastep
    # keeps the in-trace collectives inside the scan, so a multi-process
    # run pays EXACTLY the single-device dispatch schedule; a regression
    # back to the per-iteration sync driver (the pre-round-12 eviction)
    # moves it to >= 3. `mp_ranks_agree` (1.0 = both ranks emitted the
    # byte-identical model) guards SPMD consistency vacuity.
    _RESULT["mp_dispatches_per_iter"] = None
    _RESULT["mp_ranks_agree"] = None
    try:
        mp_rows = int(os.environ.get("BENCH_MICRO_MP_ROWS", n_rows))
        reports = _micro_multiproc_leg(
            X[:mp_rows], y[:mp_rows], n_iters,
            dict({k: v for k, v in params.items()
                  if k != "telemetry_out"}, tree_learner="data"))
        mp_iters = max(1, int(reports[0]["iterations"]))
        _RESULT["mp_dispatches_per_iter"] = round(
            float(reports[0]["dispatches"]) / mp_iters, 4)
        _RESULT["mp_ranks_agree"] = float(
            reports[0]["model"] == reports[1]["model"])
        _RESULT["mp_fast_path"] = bool(reports[0]["fast_path"])
        _RESULT["mp_iterations_kept"] = mp_iters
    except Exception as e:
        print(f"multiproc leg failed: {e}", file=sys.stderr)
    for p in (tel_path, tel_eval, tel_ckpt, tel_obs, tel_ctl, tel_ing,
              tel_hb, tel_hc, tel_drift, tel_slo):
        try:
            os.remove(p)
        except OSError:
            pass
    _emit()


_MP_WORKER = '''
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
cache = os.environ.get("JAX_CACHE_DIR", "/tmp/lgbm_tpu_jax_cache_bench")
jax.config.update("jax_compilation_cache_dir", cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=int(sys.argv[2]), process_id=int(sys.argv[3]))
import lightgbm_tpu as lgb

train_path, out_path = sys.argv[4], sys.argv[5]
params = json.loads(sys.argv[6])
rounds = int(sys.argv[7])
ds = lgb.Dataset(train_path, params={"label_column": 0, "verbose": -1,
                                     "max_bin": 63})
bst = lgb.train(dict(params, num_iterations=rounds), ds)
g = bst._gbdt
c = bst.telemetry().get("counters", {})
with open(out_path, "w") as fh:
    json.dump({"rank": jax.process_index(),
               "dispatches": float(c.get("train.dispatches", 0)),
               "iterations": int(c.get("iterations", rounds)),
               "fast_path": bool(g._fast_path_ok()),
               "model": bst.model_to_string()}, fh)
'''


def _micro_multiproc_leg(X, y, n_iters, params):
    """Run the 2-process joint training and return both rank reports.
    The worker subprocesses carry the REAL product path end to end
    (loader rank-sharding -> MultiProcLayout -> shard_map growers in the
    megastep scan); the parent only compares their reports."""
    import socket
    import subprocess
    import tempfile
    mp_dir = tempfile.mkdtemp(prefix="bench_micro_mp_")
    train_csv = os.path.join(mp_dir, "train.csv")
    with open(train_csv, "w") as fh:
        for i in range(len(y)):
            fh.write(",".join([f"{y[i]:g}"]
                              + [repr(float(v)) for v in X[i]]) + "\n")
    worker_py = os.path.join(mp_dir, "worker.py")
    with open(worker_py, "w") as fh:
        fh.write(_MP_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    wp = dict(params)
    outs = [os.path.join(mp_dir, f"rank{i}.json") for i in range(2)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # ONLY the repo on the path (same rule as the multiproc tests): the
    # package must be importable from the workers' cwd-less interpreter,
    # and the axon TPU plugin breaks multiprocess CPU backends
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    env.pop("XLA_FLAGS", None)
    procs = []
    for i in range(2):
        wp_i = dict(wp, telemetry_out=os.path.join(
            mp_dir, f"tel_rank{i}.jsonl"))
        procs.append(subprocess.Popen(
            [sys.executable, worker_py, coord, "2", str(i), train_csv,
             outs[i], json.dumps(wp_i), str(n_iters)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    try:
        for i, p in enumerate(procs):
            out, err = p.communicate(
                timeout=int(os.environ.get("BENCH_MICRO_MP_TIMEOUT",
                                           1200)))
            if p.returncode != 0:
                raise RuntimeError(
                    f"mp worker rank {i} exited {p.returncode}: "
                    + err.decode(errors="replace")[-2000:])
        reports = [json.load(open(o)) for o in outs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        import shutil as _sh
        _sh.rmtree(mp_dir, ignore_errors=True)
    # the model strings embed per-rank telemetry_out paths; normalize so
    # rank agreement compares the MODEL, not the config echo
    for i, r in enumerate(reports):
        r["model"] = r["model"].replace(f"tel_rank{i}.jsonl",
                                        "tel_rank.jsonl")
    return reports


def run_serve() -> None:
    """Prediction-serving bench (``--serve``; add ``--micro`` for the
    deterministic CPU mode CI gates on).

    Two legs against a live ``lightgbm_tpu.serve.PredictionService``:

    - **closed loop** — sequential mixed-size requests, one at a time:
      per-request latency (p50 is the headline) plus the two
      DETERMINISTIC counters the regression gate keys on:
      ``dispatches_per_request`` (bucketing keeps it at exactly 1.0 —
      a chunking/bucketing regression moves it) and
      ``compiles_per_1k_requests`` (0 after warmup — a bucket-shape
      leak recompiling per request size moves it to ~1000/len(sizes));
    - **open loop** — all requests submitted concurrently so the
      micro-batcher coalesces: throughput + observed batching ratio
      (timing-dependent, recorded informationally, never gated).
    """
    micro = "--micro" in sys.argv[1:]
    if micro:
        os.environ["JAX_PLATFORMS"] = "cpu"   # before any jax import
    _RESULT.update(metric="serve_micro_p50_ms" if micro
                   else "serve_p50_ms", unit="ms", vs_baseline=None)
    _install_guards()
    _phase("serve_start")
    if not micro:
        from lightgbm_tpu.utils.platform import pin_jax_platforms
        pin_jax_platforms()   # the axon plugin ignores the env var
    import jax
    if micro:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_CACHE_DIR",
                                     "/tmp/lgbm_tpu_jax_cache_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve import PredictionService

    n_models = int(os.environ.get("SERVE_MODELS", 2))
    n_requests = int(os.environ.get("SERVE_REQUESTS", 200))
    train_rows = int(os.environ.get("SERVE_TRAIN_ROWS",
                                    2000 if micro else 200_000))
    n_feat = 12
    max_batch = int(os.environ.get("SERVE_MAX_BATCH_ROWS", 1024))
    _RESULT["bench_config"] = {"mode": "serve_micro" if micro else "serve",
                               "models": n_models, "requests": n_requests,
                               "train_rows": train_rows,
                               "max_batch_rows": max_batch}
    _RESULT["platform"] = "cpu" if micro else None

    models = {}
    for m in range(n_models):
        X, y = _make_data(train_rows, n_feat)
        rngm = np.random.RandomState(100 + m)
        y = (X @ rngm.randn(n_feat) > 0).astype(np.float32)
        models[f"m{m}"] = lgb.train(
            {"objective": "binary", "num_leaves": 31, "verbose": -1,
             "metric": "None", "max_bin": 63},
            lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1}),
            num_boost_round=int(os.environ.get("SERVE_TREES", 20)))
    _phase("serve_models_trained")

    # live exporter ON for the whole bench: the deterministic counters
    # below (dispatches_per_request == 1.0, compiles_per_1k == 0) are
    # measured WITH the observability plane active, so the CI absolute
    # gate doubles as the exporter-on/off equality check — the off
    # values are the contract itself
    serve_metrics_port = _free_port()
    # single lane: the closed/open-loop legs are the trajectory's
    # longest-lived comparable series — they keep measuring the ONE
    # bounded queue regardless of how many host devices the runner
    # forces; the fleet leg below measures the multi-device plane
    svc = PredictionService(models, max_batch_rows=max_batch,
                            max_delay_ms=1.0, min_bucket_rows=16,
                            batch_events=False, serve_devices=1,
                            metrics_port=serve_metrics_port)
    svc.warmup()
    _phase("serve_warmup_ok")

    # ---- closed loop: deterministic request stream, one in flight ----
    rng = np.random.RandomState(7)
    sizes = rng.randint(1, max_batch + 1, size=n_requests)
    mids = [f"m{i % n_models}" for i in range(n_requests)]
    reqs = [rng.rand(int(s), n_feat).astype(np.float32) for s in sizes]
    s0 = svc.stats()
    lat = []
    t0 = time.perf_counter()
    for mid, Xq in zip(mids, reqs):
        r0 = time.perf_counter()
        svc.predict(mid, Xq)
        lat.append((time.perf_counter() - r0) * 1000.0)
    closed_wall = time.perf_counter() - t0
    s1 = svc.stats()
    lat.sort()

    def q(p):
        return lat[min(len(lat) - 1, int(p * (len(lat) - 1) + 0.5))]

    _RESULT["value"] = round(q(0.50), 4)
    _RESULT["p95_ms"] = round(q(0.95), 4)
    _RESULT["p99_ms"] = round(q(0.99), 4)
    d_disp = s1["dispatches"] - s0["dispatches"]
    d_comp = s1["compiles"] - s0["compiles"]
    _RESULT["dispatches_per_request"] = round(d_disp / n_requests, 6)
    _RESULT["compiles_per_1k_requests"] = round(
        d_comp * 1000.0 / n_requests, 6)
    _RESULT["closed_loop_rows_per_s"] = round(
        float(sizes.sum()) / closed_wall, 1)
    # mid-run scrape: the service is still live (open loop follows) —
    # the exporter must answer NOW with the requests already counted
    # (the serve-smoke CI job asserts exporter_requests_total > 0)
    try:
        from lightgbm_tpu.obs.export import scrape
        _, body = scrape(svc.metrics_url, timeout=10)
        line = next(l for l in body.splitlines()
                    if l.startswith("lgbm_serve_requests_total"))
        _RESULT["exporter_requests_total"] = int(
            float(line.rsplit(" ", 1)[1]))
    except Exception as e:
        print(f"serve exporter scrape failed: {e}", file=sys.stderr)
        _RESULT["exporter_requests_total"] = 0
    _phase("serve_closed_ok")
    _emit()   # the deterministic gate numbers are on stdout now

    # ---- open loop: concurrent submits exercise the micro-batcher ----
    t0 = time.perf_counter()
    futs = [svc.submit(mid, Xq) for mid, Xq in zip(mids, reqs)]
    for f in futs:
        f.result(timeout=600)
    open_wall = time.perf_counter() - t0
    s2 = svc.stats()
    _RESULT["open_loop_rows_per_s"] = round(
        float(sizes.sum()) / open_wall, 1)
    ob = s2["batches"] - s1["batches"]
    _RESULT["open_loop_batches"] = ob
    _RESULT["open_loop_requests_per_batch"] = round(
        n_requests / max(1, ob), 3)
    _RESULT["serve_stats"] = {
        k: s2[k] for k in ("requests", "batches", "dispatches", "compiles",
                           "evictions", "degradations")}
    _RESULT["latency_ms"] = s2.get("latency_ms")
    _phase("serve_open_ok")
    svc.close()

    # ---- overload leg: open-loop offered load >> capacity ----------
    # The ROADMAP-mandated acceptance numbers: with a bounded queue, a
    # deadline and a dispatch gate that makes capacity << offered load
    # DETERMINISTIC on any runner, the service must shed/reject the
    # excess with structured errors, keep the queue at its bound and
    # leave ZERO futures unresolved.  shed_ratio is exact by
    # construction (the queue fills to its request bound, the gate
    # outlasts every queued deadline, so exactly the bound sheds);
    # reject_ratio varies only by the first batch's coalesce count.
    import threading as _threading

    from lightgbm_tpu.serve import (ServeDeadlineExceeded, ServeError,
                                    ServeRejected)
    q_bound = int(os.environ.get("SERVE_OVERLOAD_QUEUE", 24))
    n_offered = int(os.environ.get("SERVE_OVERLOAD_REQUESTS", 240))
    svc3 = PredictionService({"m0": models["m0"]}, max_batch_rows=64,
                             max_delay_ms=0.5, min_bucket_rows=16,
                             batch_events=False, serve_devices=1,
                             max_queue_requests=q_bound,
                             default_deadline_ms=250.0)
    svc3.warmup()
    real_dispatch = svc3.batcher._dispatch
    gate = _threading.Event()

    def gated(mid, Xg):
        gate.wait(5.0)
        return real_dispatch(mid, Xg)
    svc3.batcher._dispatch = gated
    rng_o = np.random.RandomState(11)
    reqs_o = [rng_o.rand(8, n_feat).astype(np.float32)
              for _ in range(n_offered)]
    done_lat = {}
    futs_o, rejected = [], 0
    for Xq in reqs_o:
        try:
            fut = svc3.submit("m0", Xq)
            t_sub = time.perf_counter()
            # keyed by the future itself: rejections interleave with
            # admissions, so positional indices would mispair latencies
            fut.add_done_callback(
                lambda f, t=t_sub:
                done_lat.__setitem__(id(f), time.perf_counter() - t))
            futs_o.append(fut)
        except ServeRejected:
            rejected += 1
    time.sleep(0.6)   # queued deadlines (250 ms) all expire
    gate.set()
    served = shed = unresolved = 0
    for fut in futs_o:
        try:
            fut.result(timeout=60)
            served += 1
        except ServeDeadlineExceeded:
            shed += 1
        except ServeError:
            shed += 1        # structured either way; bucket with shed
        except Exception:
            unresolved += 1
    snap3 = svc3.tel.snapshot()
    peak = int(snap3.get("gauges", {}).get("serve.queue_peak_requests",
                                           0))
    lat_ok = sorted(1000.0 * done_lat[id(f)] for f in futs_o
                    if f.exception() is None and id(f) in done_lat)
    _RESULT["shed_ratio"] = round(shed / n_offered, 6)
    _RESULT["reject_ratio"] = round(rejected / n_offered, 6)
    _RESULT["overload_p99_ms"] = round(
        lat_ok[min(len(lat_ok) - 1,
                   int(0.99 * (len(lat_ok) - 1) + 0.5))], 3) \
        if lat_ok else None
    _RESULT["overload_unresolved"] = unresolved
    _RESULT["overload_queue_overflow"] = max(0, peak - q_bound)
    _RESULT["overload_served"] = served
    svc3.close(drain_timeout_s=10)
    _phase("serve_overload_ok")

    # ---- rollover-under-load leg -----------------------------------
    # Continuous closed-loop traffic across a rollover(): the swap is
    # one dict assignment under the residency lock, so the dropped-
    # request count is deterministically ZERO (gated in serve-chaos CI
    # and by bench_compare).
    svc4 = PredictionService({"m": models["m0"]}, max_batch_rows=256,
                             max_delay_ms=0.5, min_bucket_rows=16,
                             batch_events=False)
    svc4.warmup()
    stop_t = _threading.Event()
    roll_failures, roll_served = [], [0]

    def _traffic(seed):
        rt = np.random.RandomState(seed)
        while not stop_t.is_set():
            try:
                svc4.predict("m", rt.rand(4, n_feat).astype(np.float32),
                             timeout=60)
                roll_served[0] += 1
            except Exception as e:   # any failure IS the regression
                roll_failures.append(repr(e))
    traffic_threads = [_threading.Thread(target=_traffic, args=(21 + i,),
                                         daemon=True) for i in range(2)]
    for th in traffic_threads:
        th.start()
    time.sleep(0.2)
    # the candidate must be a DIFFERENT model state so the hash-changed
    # gate is meaningful even under SERVE_MODELS=1 (a one-tree-trimmed
    # copy — no retraining cost)
    if n_models > 1:
        roll_to = models["m1"]
    else:
        m0 = models["m0"]
        roll_to = lgb.Booster(model_str=m0.model_to_string(
            num_iteration=max(1, m0.num_trees() - 1)))
    roll_rep = svc4.rollover("m", roll_to)
    time.sleep(0.2)
    stop_t.set()
    for th in traffic_threads:
        th.join(timeout=30)
    svc4.close(drain_timeout_s=30)
    _RESULT["rollover_dropped_requests"] = len(roll_failures)
    _RESULT["rollover_requests_served"] = roll_served[0]
    _RESULT["rollover_hash_changed"] = float(
        roll_rep["promoted"]
        and roll_rep["old_hash"] != roll_rep["new_hash"])
    _phase("serve_rollover_ok")

    # ---- fleet leg: replicated multi-device serving ----------------
    # Three sub-legs against one serve_devices=all service (the
    # serve-fleet CI job forces 4 host devices via XLA_FLAGS; on a
    # 1-device runner everything below degenerates to the single-lane
    # plane and the scaling ratio sits at ~1.0):
    #
    # 1. closed loop, REAL dispatches -> the per-device deterministic
    #    contract: every device that took traffic measured exactly 1.0
    #    dispatches/request and 0 steady-state compiles, and the
    #    round-robin tie-break routed EVERY device
    #    (fleet_unrouted_devices == 0);
    # 2. open loop with a fixed per-batch dispatch floor on BOTH a
    #    1-lane service and the fleet -> rows/s scaling that is
    #    deterministic on any runner speed (the floor dominates, so the
    #    ratio measures lane overlap, not CPU contention);
    # 3. predict_bulk -> row-sharded scoring over the mesh must be
    #    numerically identical (f32 tolerance) to the single-device
    #    dispatch path, with its throughput recorded.
    fleet_n = len(jax.local_devices())
    _RESULT["fleet_devices"] = fleet_n
    svcF = PredictionService({"m0": models["m0"]},
                             max_batch_rows=max_batch,
                             max_delay_ms=1.0, min_bucket_rows=16,
                             batch_events=False, serve_devices=0)
    svcF.warmup()
    _phase("serve_fleet_warmup_ok")

    n_fleet = int(os.environ.get("SERVE_FLEET_REQUESTS", 32)) * fleet_n
    rng_f = np.random.RandomState(17)
    sizes_f = rng_f.randint(1, 257, size=n_fleet)
    reqs_f = [rng_f.rand(int(s), n_feat).astype(np.float32)
              for s in sizes_f]
    for Xq in reqs_f:
        svcF.predict("m0", Xq)
    sF = svcF.stats()
    per_f = sF.get("fleet", {}).get("per_device")
    if per_f is None:      # 1-device runner: no fleet section
        per_f = [{"device": 0, "requests": sF["requests"],
                  "dispatches_per_request":
                      sF["dispatches_per_request"],
                  "compiles_per_1k_requests":
                      sF["compiles_per_1k_requests"], "spills": 0}]
    routed = sum(1 for e in per_f if e.get("requests", 0) > 0)
    _RESULT["routed_devices"] = routed
    _RESULT["fleet_unrouted_devices"] = fleet_n - routed
    dprs = [e["dispatches_per_request"] for e in per_f
            if "dispatches_per_request" in e]
    c1ks = [e["compiles_per_1k_requests"] for e in per_f
            if "compiles_per_1k_requests" in e]
    _RESULT["fleet_dispatches_per_request_worst"] = \
        max(dprs, key=lambda v: abs(v - 1.0)) if dprs else None
    _RESULT["fleet_compiles_per_1k_worst"] = \
        max(c1ks) if c1ks else None
    _RESULT["fleet_spills"] = int(
        sF.get("fleet", {}).get("spills", 0))
    _phase("serve_fleet_closed_ok")

    # open-loop scaling: identical request stream, identical per-batch
    # floor; requests sized to max_batch_rows so one request == one
    # batch on both topologies (coalescing differences would otherwise
    # let the 1-lane backlog batch more rows per floor payment)
    # the floor must DOMINATE the real per-batch dispatch (~2-7 ms for
    # 16 rows on a loaded CPU): real dispatches serialize on a small
    # runner's cores, so a thin floor would measure CPU contention
    # instead of lane overlap and under-report the scaling (measured:
    # a 25 ms floor reads ~2.7-3.3x and a 50 ms floor still dips to
    # ~2.95x on a busy 1-core box; at 100 ms the predicted 4-lane
    # scaling (100+r)/(25+r) stays >= 3.1x out to r = 10 ms of real
    # serialized dispatch, which keeps the gate margin even under
    # heavy co-tenancy)
    floor_s = float(os.environ.get("SERVE_FLEET_FLOOR_MS", 100.0)) / 1000.0
    # tiny requests: the real dispatch must stay a sliver of the floor
    # even when a 1-core runner serializes every lane's device work
    scale_rows = 16
    n_scale = int(os.environ.get("SERVE_FLEET_SCALE_REQUESTS", 40)) \
        * max(1, fleet_n)
    rng_s = np.random.RandomState(23)
    reqs_s = [rng_s.rand(scale_rows, n_feat).astype(np.float32)
              for _ in range(n_scale)]

    def _floored_open_loop(svc_x):
        real_x = svc_x.batcher._dispatch

        def floored(*a):
            time.sleep(floor_s)
            return real_x(*a)
        svc_x.batcher._dispatch = floored
        t0x = time.perf_counter()
        fs = [svc_x.submit("m0", Xq) for Xq in reqs_s]
        for f in fs:
            f.result(timeout=600)
        wall = time.perf_counter() - t0x
        svc_x.batcher._dispatch = real_x
        return n_scale * scale_rows / wall

    svcS = PredictionService({"m0": models["m0"]},
                             max_batch_rows=scale_rows,
                             max_delay_ms=1.0, min_bucket_rows=16,
                             batch_events=False, serve_devices=1)
    svcS.warmup()
    rate_1dev = _floored_open_loop(svcS)
    svcS.close()
    svcF.batcher.max_batch_rows = scale_rows
    rate_fleet = _floored_open_loop(svcF)
    svcF.batcher.max_batch_rows = max_batch
    _RESULT["fleet_rows_per_s_1dev"] = round(rate_1dev, 1)
    _RESULT["fleet_rows_per_s"] = round(rate_fleet, 1)
    _RESULT["fleet_scaling_x"] = round(rate_fleet / rate_1dev, 3)
    _phase("serve_fleet_scaling_ok")

    # bulk identity + throughput: warm call compiles the sharded
    # executable, the timed call measures steady-state rows/s
    Xb = np.random.RandomState(29).rand(
        int(os.environ.get("SERVE_BULK_ROWS", 20_000)),
        n_feat).astype(np.float32)
    svcF.predict_bulk("m0", Xb[:256])
    t0b = time.perf_counter()
    out_bulk = svcF.predict_bulk("m0", Xb)
    bulk_wall = time.perf_counter() - t0b
    out_single = svcF.predict("m0", Xb)
    bulk_diff = float(np.max(np.abs(out_bulk - out_single)))
    bulk_ok = bool(np.allclose(out_bulk, out_single,
                               rtol=1e-5, atol=1e-6))
    _RESULT["bulk_rows_per_s"] = round(Xb.shape[0] / bulk_wall, 1)
    _RESULT["bulk_max_abs_diff"] = bulk_diff
    _RESULT["bulk_identity_ok"] = float(bulk_ok)
    _RESULT["bulk_identity_mismatch"] = float(not bulk_ok)
    svcF.close()
    _phase("serve_fleet_ok")
    _emit()

    # ---- slo forced-alert leg: deterministic alert lifecycle ---------
    # A serve_slow_dispatch fault injects ONE ~400 ms dispatch into an
    # slo-armed service whose latency objective is overridden down to
    # 50 ms with hysteresis 2 (the rest of the built-in catalog stays
    # armed, so any OTHER objective firing here is a false positive).
    # tick_period 0 disables the ticker — every evaluation below is an
    # explicit forced step, which makes the lifecycle exact on any
    # runner: two breaching evaluations fire the alert and capture the
    # incident artifact, ~300 fast requests push the slow sample past
    # the p99 index, two clean evaluations resolve it. Exactly one
    # firing->resolved cycle, a schema-valid incident and
    # slo_false_positives == 0 are gated absolutely by the
    # serve-alert-smoke CI job and bench_compare's deterministic set.
    import tempfile
    slo_dir = tempfile.mkdtemp(prefix="bench_serve_slo_")
    slo_cfg = os.path.join(slo_dir, "slo.json")
    slo_tel = os.path.join(slo_dir, "tel.jsonl")
    with open(slo_cfg, "w") as fh:
        json.dump({"objectives": [
            {"id": "serve.latency_p99", "target": 50.0,
             "hysteresis": 2, "resolve_hysteresis": 2}]}, fh)
    svc5 = PredictionService({"m0": models["m0"]}, max_batch_rows=64,
                             max_delay_ms=0.5, min_bucket_rows=16,
                             batch_events=False, serve_devices=1,
                             slo_config=slo_cfg, slo_tick_period_s=0.0,
                             metrics_port=_free_port(),
                             telemetry_out=slo_tel)
    svc5.warmup()
    s5_warm = svc5.stats()              # baseline: warmup dispatches
    # arm the fault only AFTER warmup so the slow dispatch lands on the
    # measured request (the hook re-reads the env per batch); restore
    # the previous value either way
    prev_faults = os.environ.get("LIGHTGBM_TPU_FAULTS")
    os.environ["LIGHTGBM_TPU_FAULTS"] = "serve_slow_dispatch@1:ms=400"
    Xs = np.random.RandomState(31).rand(4, n_feat).astype(np.float32)
    try:
        svc5.predict("m0", Xs)          # ~400 ms: the breaching sample
    finally:
        if prev_faults is None:
            os.environ.pop("LIGHTGBM_TPU_FAULTS", None)
        else:
            os.environ["LIGHTGBM_TPU_FAULTS"] = prev_faults
    eng = svc5.slo
    eng.step(force=True)
    eng.step(force=True)                # hysteresis 2 -> firing
    for _ in range(300):                # refill the latency ring fast
        svc5.predict("m0", Xs)
    eng.step(force=True)
    eng.step(force=True)                # resolve_hysteresis 2 -> clear
    # live /alerts endpoint + build-info series while the svc is up
    try:
        from lightgbm_tpu.obs.export import scrape as _scr5
        base5 = svc5.metrics_url.rsplit("/metrics", 1)[0]
        _, abody = _scr5(f"{base5}/alerts", timeout=10)
        _RESULT["slo_alerts_endpoint_ok"] = float(
            int(json.loads(abody).get("fired", 0)) >= 1)
        _, mbody = _scr5(svc5.metrics_url, timeout=10)
        _RESULT["slo_build_info_ok"] = float(any(
            l.startswith("lgbm_build_info{") and l.rstrip().endswith(" 1")
            for l in mbody.splitlines()))
    except Exception as e:
        print(f"slo endpoint scrape failed: {e}", file=sys.stderr)
        _RESULT["slo_alerts_endpoint_ok"] = 0.0
        _RESULT["slo_build_info_ok"] = 0.0
    pay = eng.alerts_payload()
    s5 = svc5.stats()
    svc5.close()
    hist = pay.get("history", [])
    fired5 = [h for h in hist if h.get("state") == "firing"]
    _RESULT["slo_alert_fired"] = len(fired5)
    _RESULT["slo_alert_resolved"] = len(
        [h for h in hist if h.get("state") == "resolved"])
    _RESULT["slo_false_positives"] = len(
        [h for h in fired5
         if h.get("objective") != "serve.latency_p99"])
    inc_ok = 0.0
    try:
        with open(pay["incidents"][0]) as fh:
            inc = json.load(fh)
        inc_ok = float(
            inc.get("schema") == "lightgbm_tpu.incident/1"
            and inc.get("alert", {}).get("objective")
            == "serve.latency_p99"
            and isinstance(inc.get("telemetry"), dict)
            and isinstance(inc.get("context"), dict))
    except Exception as e:
        print(f"slo incident check failed: {e}", file=sys.stderr)
    _RESULT["slo_incident_valid"] = inc_ok
    # inverted forms for bench_compare's zero-to-nonzero gate (the
    # ratio gate only flags increases, so "must stay 1" contracts are
    # expressed as "must stay 0" failures)
    _RESULT["slo_incident_invalid"] = 1.0 - inc_ok
    _RESULT["slo_alert_missed"] = float(
        _RESULT["slo_alert_fired"] != 1)
    _RESULT["slo_alert_unresolved"] = float(
        _RESULT["slo_alert_resolved"] != _RESULT["slo_alert_fired"])
    _RESULT["slo_dispatches_per_request"] = round(
        (s5["dispatches"] - s5_warm["dispatches"])
        / max(1, s5["requests"] - s5_warm["requests"]), 6)
    import shutil as _sh5
    _sh5.rmtree(slo_dir, ignore_errors=True)
    _phase("serve_slo_alert_ok")
    _emit()


def main() -> None:
    if "--serve" in sys.argv[1:]:
        run_serve()
        return
    if "--micro" in sys.argv[1:]:
        run_micro()
        return
    _install_guards()
    # the TIMETAG timer collects section times for the failure tail (its
    # sections carry no sync points, so the pipelined hot loop stays hot)
    from lightgbm_tpu.utils.timer import global_timer
    global_timer.enable()
    _phase("start")

    # chip-health probe FIRST, in a bounded subprocess: the tunnel's
    # delivered throughput swings >10x over hours and its failure mode is
    # an infinite hang at backend init (PROFILE.md §5) — record the state
    # and bail with a parseable record instead of dying silently
    probe, probe_err = _probe_chip()
    if probe is None:
        print(f"chip probe failed: {probe_err}", file=sys.stderr)
        _phase(f"probe_failed:{probe_err[:60]}")
        if "tunnel_stuck" in probe_err:
            # backend init hangs: the perf leg would hang forever too —
            # emit the record and stop
            _die_with_record(probe_err)
        # transient probe error: advisory only, keep the perf leg alive
        _RESULT["probe_error"] = probe_err
        tfs = 0.0
    else:
        tfs = float(probe.get("probe_tfs", 0.0))
        _RESULT["probe_tfs"] = tfs
        _RESULT["platform"] = probe.get("platform")
        _phase(f"probe_ok:{tfs:.1f}tfs")
        print(f"chip probe: {tfs:.1f} TF/s (chained bf16 4096^3 matmul; "
              f"v5e spec 197)", file=sys.stderr)

    from lightgbm_tpu.utils.platform import pin_jax_platforms
    pin_jax_platforms()   # the axon plugin ignores the env var
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_CACHE_DIR",
                                     "/tmp/lgbm_tpu_jax_cache_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    n_rows = int(os.environ.get("BENCH_ROWS", 10_500_000))
    n_feat = 28
    n_iters = int(os.environ.get("BENCH_ITERS", 10))
    # the trajectory record carries the run shape so bench_compare.py
    # only diffs like-for-like (a 20k-row smoke next to a full run would
    # otherwise flag order-of-magnitude fake regressions)
    _RESULT["bench_config"] = {"rows": n_rows, "iters": n_iters}
    baseline_sec_per_iter = 130.094 / 500  # ref: docs/Experiments.rst:113

    X, y = _make_data(n_rows, n_feat)

    sec_per_iter = None
    for engine in ("fused", "frontier", "xla"):
        # the axon remote-compile tunnel drops connections transiently
        # ("response body closed", HTTP 500 transport hiccups) — retry
        # before degrading to a slower engine
        for attempt in range(3):
            try:
                sec_per_iter = _run(engine, X, y, n_iters)
                print(f"bench engine: {engine}", file=sys.stderr)
                _phase(f"perf_{engine}_ok")
                break
            except Exception as e:  # degrade, don't zero the round
                msg = str(e)
                _phase(f"perf_{engine}_attempt{attempt}_failed:"
                       f"{type(e).__name__}")
                print(f"bench engine {engine} attempt {attempt} failed: "
                      f"{type(e).__name__}: {msg[:500]}", file=sys.stderr)
                transient = ("remote_compile" in msg or "INTERNAL" in msg
                             or "read body" in msg
                             or "response body" in msg)
                if not transient:
                    break
                time.sleep(20)
        if sec_per_iter is not None:
            break
    if sec_per_iter is None:
        _die_with_record("all_engines_failed")

    scaled = sec_per_iter * (10_500_000 / n_rows)
    _RESULT["value"] = round(scaled, 4)
    _RESULT["vs_baseline"] = round(baseline_sec_per_iter / scaled, 3)
    _RESULT["engine"] = engine
    _emit()   # the perf record is now on stdout, whatever happens next

    # quality leg: differential AUC vs the rebuilt reference CPU package
    # (skippable with BENCH_QUALITY=0). Iteration budget scales with the
    # probe: the full 500-iter leg is only feasible at healthy throughput
    # (~40+ TF/s); a degraded chip gets a shrunk leg with the reason
    # recorded rather than a destroyed round.
    if os.environ.get("BENCH_QUALITY", "1") != "0":
        full_iters = int(os.environ.get("BENCH_QUALITY_ITERS", 500))
        if probe is None:
            _RESULT["quality_skipped"] = "no_probe_reading"
            print("quality leg skipped: no probe reading", file=sys.stderr)
        elif tfs < 8.0:
            _RESULT["quality_skipped"] = f"probe_{tfs:.1f}_tfs_too_low"
            print(f"quality leg skipped: probe {tfs:.1f} TF/s",
                  file=sys.stderr)
        else:
            q_iters = full_iters if tfs >= 40.0 else \
                min(full_iters, max(100, int(full_iters * tfs / 40.0)))
            _RESULT["quality_iters"] = q_iters
            try:
                _RESULT.update(_quality_leg(engine, iters=q_iters))
                _phase("quality_ok")
            except Exception as e:
                print(f"quality leg failed: {type(e).__name__}: "
                      f"{str(e)[:300]}", file=sys.stderr)
                _phase(f"quality_failed:{type(e).__name__}")
                _RESULT["quality_error"] = f"{type(e).__name__}"
                _attach_tail()   # leave the where-did-the-time-go trail
        _emit()   # merged record; last stdout line wins


if __name__ == "__main__":
    main()
