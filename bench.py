"""Benchmark: Higgs-shaped GBDT training throughput on one TPU chip.

Mirrors the reference's headline benchmark configuration
(ref: docs/GPU-Performance.rst:108-123 — Higgs, max_bin=63, num_leaves=255,
lr=0.1; docs/Experiments.rst:113 — CPU LightGBM trains Higgs 10.5M×28 in
130.094 s / 500 iterations = 0.2602 s/iter on 2×E5-2690v4).

Prints ONE JSON line:
  {"metric": "higgs_sec_per_iter_10.5M_rows", "value": ..., "unit": "s",
   "vs_baseline": baseline/ours (>1 means faster than reference CPU)}

The synthetic matrix is Higgs-shaped (N×28 dense float features with
correlated signal); time is measured per boosting iteration after warmup and
scaled linearly to 10.5M rows (histogram construction, the dominant cost, is
linear in rows — ref: dense_bin.hpp ConstructHistogram).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.boosting.gbdt import (feature_meta_from_dataset,
                                            split_params_from_config)
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset import TpuDataset
    from lightgbm_tpu.models.learner import grow_tree_depthwise

    # Higgs shape: 28 features; rows sized to fit comfortably in HBM,
    # result scaled to the reference's 10.5M rows.
    n_rows = int(os.environ.get("BENCH_ROWS", 2_000_000))
    n_feat = 28
    num_leaves = 255
    max_bin = 63
    n_iters = int(os.environ.get("BENCH_ITERS", 10))
    baseline_sec_per_iter = 130.094 / 500  # ref: docs/Experiments.rst:113

    rng = np.random.RandomState(0)
    X = rng.rand(n_rows, n_feat).astype(np.float32)
    w = rng.randn(n_feat).astype(np.float32)
    y = (X @ w + 0.5 * rng.randn(n_rows) > 0).astype(np.float32)

    cfg = Config({"max_bin": max_bin, "num_leaves": num_leaves,
                  "verbose": -1})
    ds = TpuDataset.from_data(X, cfg)
    ds.metadata.set_label(y)
    del X

    meta = feature_meta_from_dataset(ds)
    params = split_params_from_config(cfg)
    B = int(ds.max_num_bin)
    F = ds.num_features
    bins = jnp.asarray(ds.bins)
    label = jnp.asarray(y)
    feature_mask = jnp.ones((F,), bool)

    @jax.jit
    def boost_iter(score):
        lv = jnp.where(label > 0, 1.0, -1.0)
        response = -lv / (1.0 + jnp.exp(lv * score))
        grad = response
        hess = jnp.abs(response) * (1.0 - jnp.abs(response))
        gh = jnp.stack([grad, hess, jnp.ones_like(grad)], axis=1)
        tree, row_leaf = grow_tree_depthwise(
            bins, gh, meta, feature_mask, params, num_leaves, B,
            hist_impl="segment")
        return score + 0.1 * tree.leaf_value[row_leaf], tree

    score = jnp.zeros((n_rows,), jnp.float32)
    # warmup/compile
    score, tree = boost_iter(score)
    jax.block_until_ready(score)

    t0 = time.perf_counter()
    for _ in range(n_iters):
        score, tree = boost_iter(score)
    jax.block_until_ready(score)
    elapsed = time.perf_counter() - t0

    sec_per_iter = elapsed / n_iters
    scaled = sec_per_iter * (10_500_000 / n_rows)
    print(json.dumps({
        "metric": "higgs_sec_per_iter_10.5M_rows",
        "value": round(scaled, 4),
        "unit": "s",
        "vs_baseline": round(baseline_sec_per_iter / scaled, 3),
    }))


if __name__ == "__main__":
    main()
