"""Benchmark: Higgs-shaped GBDT training throughput on one TPU chip.

Mirrors the reference's headline benchmark configuration
(ref: docs/GPU-Performance.rst:108-123 — Higgs, max_bin=63, num_leaves=255,
lr=0.1; docs/Experiments.rst:113 — CPU LightGBM trains Higgs 10.5M×28 in
130.094 s / 500 iterations = 0.2602 s/iter on 2×E5-2690v4).

Drives the full product path (lightgbm_tpu.train -> GBDT driver -> fused
route+histogram Pallas engine) at the REAL 10.5M-row scale by default
(BENCH_ROWS scales down for smoke runs) and prints ONE JSON line:
  {"metric": "higgs_sec_per_iter_10.5M_rows", "value": ..., "unit": "s",
   "vs_baseline": baseline/ours (>1 means faster than reference CPU)}

Engines are tried in order (fused -> frontier -> xla): a kernel that fails
to compile on the attached chip must degrade, not zero the round.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _make_data(n_rows: int, n_feat: int):
    rng = np.random.RandomState(0)
    X = rng.rand(n_rows, n_feat).astype(np.float32)
    w = rng.randn(n_feat).astype(np.float32)
    y = (X @ w + 0.5 * rng.randn(n_rows) > 0).astype(np.float32)
    return X, y


def _run(engine: str, X, y, n_iters: int):
    import jax
    import lightgbm_tpu as lgb
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 1,
              "min_sum_hessian_in_leaf": 1e-3, "verbose": -1,
              "metric": "None", "tpu_engine": engine}
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    booster = lgb.Booster(params=params, train_set=ds)
    g = booster._gbdt

    def settle():
        # the driver pipelines iterations asynchronously; timing is only
        # honest if the host model list AND the device queue are settled
        if hasattr(g, "drain_pending"):
            g.drain_pending()
        jax.block_until_ready(g.scores)

    booster.update()  # warmup: compile + first tree
    settle()
    t0 = time.perf_counter()
    for _ in range(n_iters):
        booster.update()
    settle()
    return (time.perf_counter() - t0) / n_iters


def main() -> None:
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_CACHE_DIR",
                                     "/tmp/lgbm_tpu_jax_cache_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    n_rows = int(os.environ.get("BENCH_ROWS", 10_500_000))
    n_feat = 28
    n_iters = int(os.environ.get("BENCH_ITERS", 10))
    baseline_sec_per_iter = 130.094 / 500  # ref: docs/Experiments.rst:113

    X, y = _make_data(n_rows, n_feat)

    # chip-health probe: the tunnel's delivered throughput swings >10x
    # over hours (PROFILE.md §5) — record it so the headline number can
    # be read with its error bar
    try:
        import jax
        import jax.numpy as jnp
        xp = jnp.asarray(np.random.RandomState(1).randn(4096, 4096)
                         .astype(np.float32)).astype(jnp.bfloat16)

        @jax.jit
        def _chain(m):
            for _ in range(8):
                m = (m @ m) * 1e-3
            return jnp.sum(m.astype(jnp.float32))
        float(_chain(xp))
        t0 = time.perf_counter()
        float(_chain(xp))
        tfs = 8 * 2 * 4096 ** 3 / (time.perf_counter() - t0) / 1e12
        print(f"chip probe: {tfs:.1f} TF/s (chained bf16 4096^3 matmul; "
              f"v5e spec 197)", file=sys.stderr)
    except Exception:
        pass

    sec_per_iter = None
    for engine in ("fused", "frontier", "xla"):
        try:
            sec_per_iter = _run(engine, X, y, n_iters)
            print(f"bench engine: {engine}", file=sys.stderr)
            break
        except Exception as e:  # degrade, don't zero the round
            print(f"bench engine {engine} failed: {type(e).__name__}: "
                  f"{str(e)[:500]}", file=sys.stderr)
    if sec_per_iter is None:
        raise SystemExit("all engines failed")

    scaled = sec_per_iter * (10_500_000 / n_rows)
    print(json.dumps({
        "metric": "higgs_sec_per_iter_10.5M_rows",
        "value": round(scaled, 4),
        "unit": "s",
        "vs_baseline": round(baseline_sec_per_iter / scaled, 3),
    }))


if __name__ == "__main__":
    main()
