"""Per-level-pass kernel cost vs slot count on the attached chip.

Separates the one-hot build floor (Sp-independent) from the dot cost
(scales with Sp) by timing level_pass at Sp = 8..128, plus table_lookup.
Run: ROWS=10500000 python scripts/ablate_kernel.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops import fused_level as fl


def main():
    R = int(os.environ.get("ROWS", 10_500_000))
    reps = int(os.environ.get("REPS", 5))
    F, B = fl.feature_layout(28, 63)
    Rp = ((R + 2047) // 2048) * 2048   # widest tile (shallow passes)
    Fp = max(F, 8)
    rng = np.random.RandomState(0)
    bins_T = jnp.asarray(
        rng.randint(0, 63, size=(Fp, Rp)).astype(np.int8))
    leaf_T = jnp.zeros((1, Rp), jnp.int32)
    g = jnp.asarray(rng.randn(Rp).astype(np.float32))
    ones = jnp.ones((Rp,), jnp.float32)

    print(f"rows={R} (padded {Rp}) F_oh={F} B={B}")
    # tiles=0: the Sp-aware default (2048 at shallow Sp since round 4);
    # explicit 1024 reproduces the round-2/3 fixed tile for the A/B
    tile_list = [int(t) for t in
                 os.environ.get("TILES", "0,1024").split(",")]
    for nch in (5, 3):
        gh_T = fl.pack_gh(g, ones, ones, nch)
        for Sp in (1, 2, 4, 8, 16, 32, 64, 128):
            W = jnp.zeros((Sp, F * B), jnp.bfloat16).at[0, :B].set(1)
            tbl = (jnp.zeros((Sp, 128), jnp.int32)
                   .at[:, 0].set(-2).at[0, 0].set(0).at[0, 2].set(1))

            for tile in tile_list:
                # fetch-based timing: block_until_ready through the axon
                # tunnel returns early (PROFILE.md §0); chain the passes
                # data-dependently via the leaf vector and pull a scalar
                def one(lt):
                    h, nl = fl.level_pass(bins_T, lt, gh_T, W, tbl,
                                          num_slots=Sp, num_bins=B,
                                          f_oh=F, nch=nch, tile_rows=tile)
                    return h, nl
                h, nl = one(leaf_T)
                float(jnp.sum(h))
                t0 = time.perf_counter()
                lt = leaf_T
                for _ in range(reps):
                    h, lt = one(lt)
                float(jnp.sum(h))
                dt = (time.perf_counter() - t0) / reps
                bw = Fp * Rp / dt / 1e9
                eff_tile = tile or fl.default_tile_rows(Sp, F * B, nch)
                print(f"  nch={nch} Sp={Sp:4d} tile={eff_tile:5d}"
                      f"  {dt*1e3:8.1f} ms/pass  ({bw:5.1f} GB/s bins)")

    table = jnp.asarray(rng.randn(255).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, 255, size=(1, Rp)).astype(np.int32))
    out = fl.table_lookup(idx, table)
    float(jnp.sum(out))
    t0 = time.perf_counter()
    o = idx
    for _ in range(reps):
        o = fl.table_lookup(idx, table) + o[0, :1]  # data-dep chain
    float(jnp.sum(o))
    dt = (time.perf_counter() - t0) / reps
    print(f"  table_lookup 255-entry      {dt*1e3:8.1f} ms/pass")


if __name__ == "__main__":
    main()
