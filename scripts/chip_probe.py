import time
import jax, jax.numpy as jnp
import numpy as np
x = jnp.asarray(np.random.RandomState(0).randn(4096, 4096).astype(np.float32)).astype(jnp.bfloat16)
@jax.jit
def chain(x):
    for _ in range(8):
        x = (x @ x) * 1e-3
    return jnp.sum(x.astype(jnp.float32))
float(chain(x))
t0 = time.perf_counter()
float(chain(x))
dt = time.perf_counter() - t0
print(f"chained 8x4096^3 matmul: {dt*1e3:.1f} ms -> {8*2*4096**3/dt/1e12:.1f} TF/s")
