import sys, json
sys.path.insert(0, "/tmp/refpkg")
import numpy as np
import lightgbm as ref_lgb

OUT = "/root/repo/tests/fixtures"
import os
os.makedirs(OUT, exist_ok=True)

# ---- deterministic dataset (same recipe as tests/parity tests will use)
rng = np.random.RandomState(42)
R = 5000
X = np.empty((R, 6), np.float64)
X[:, 0] = rng.randn(R)                       # gaussian
X[:, 1] = rng.exponential(2.0, R)            # skewed
X[:, 2] = rng.randint(0, 10, R)              # few distinct values
X[:, 3] = np.where(rng.rand(R) < 0.7, 0.0, rng.randn(R))  # sparse-ish zeros
X[:, 4] = rng.rand(R)
X[:, 4][::7] = np.nan                        # missing
X[:, 5] = rng.randint(0, 12, R)              # categorical
w = np.array([1.0, -0.5, 0.3, 0.8, 1.2, 0.0])
logit = (X[:, 0] * w[0] + X[:, 1] * w[1] + X[:, 2] * w[2]
         + np.nan_to_num(X[:, 4]) * w[4]
         + np.isin(X[:, 5], [2, 5, 7]) * 1.5)
y = (logit + 0.3 * rng.randn(R) > 0.5).astype(np.float64)
np.save(f"{OUT}/parity_X.npy", X.astype(np.float32))
np.save(f"{OUT}/parity_y.npy", y.astype(np.float32))

params = {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
          "max_bin": 63, "min_data_in_leaf": 20, "verbose": -1,
          "deterministic": True, "force_row_wise": True, "seed": 7}
ds = ref_lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1},
                     categorical_feature=[5])
bst = ref_lgb.train(params, ds, num_boost_round=20)
bst.save_model(f"{OUT}/ref_model_binary.txt")
np.save(f"{OUT}/ref_pred_binary.npy", bst.predict(X))

from sklearn.metrics import roc_auc_score
print("ref AUC:", roc_auc_score(y, bst.predict(X)))

# ---- reference bin boundaries via a numerical-only dataset dump
ds2 = ref_lgb.Dataset(X[:, :5], label=y, params={"max_bin": 63,
                                                 "verbose": -1,
                                                 "min_data_in_bin": 3})
ds2.construct()
ds2._dump_text("/tmp/ref_dump.txt")
# the dump carries the reference's per-row BIN ASSIGNMENTS — save them as
# the bin-parity fixture (stronger than boundary equality)
rows = []
with open("/tmp/ref_dump.txt") as f:
    lines = f.read().splitlines()
start = lines.index("feature 4: ") + 1
for ln in lines[start:]:
    ln = ln.strip().rstrip(",")
    if ln:
        rows.append([int(x) for x in ln.split(",")])
arr = np.array(rows, np.int32)
assert arr.shape == (R, 5), arr.shape
np.save(f"{OUT}/ref_bins.npy", arr.astype(np.uint8))
print("fixtures written to", OUT)
# ---------------------------------------------------------------------
# Part 2: multiclass / weighted / DART / lambdarank fixtures
OUT = "/root/repo/tests/fixtures"
rng = np.random.RandomState(123)
R = 3000
X = rng.randn(R, 6).astype(np.float64)
X[::9, 3] = np.nan
np.save(f"{OUT}/parity2_X.npy", X.astype(np.float32))

# multiclass
y3 = np.argmax(X[:, :3] + 0.3 * rng.randn(R, 3), axis=1)
np.save(f"{OUT}/parity2_y_mc.npy", y3.astype(np.float32))
ds = ref_lgb.Dataset(X, label=y3, params={"verbose": -1, "max_bin": 63})
bst = ref_lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbose": -1, "max_bin": 63,
                     "deterministic": True, "force_row_wise": True,
                     "seed": 5}, ds, num_boost_round=10)
bst.save_model(f"{OUT}/ref_model_multiclass.txt")
np.save(f"{OUT}/ref_pred_multiclass.npy", bst.predict(X))

# weighted regression
yw = (X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.randn(R))
w = np.abs(rng.randn(R)) + 0.1
np.save(f"{OUT}/parity2_y_reg.npy", yw.astype(np.float32))
np.save(f"{OUT}/parity2_w.npy", w.astype(np.float32))
ds = ref_lgb.Dataset(X, label=yw, weight=w,
                     params={"verbose": -1, "max_bin": 63})
bst = ref_lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1, "max_bin": 63, "deterministic": True,
                     "force_row_wise": True, "seed": 5},
                    ds, num_boost_round=10)
bst.save_model(f"{OUT}/ref_model_weighted.txt")
np.save(f"{OUT}/ref_pred_weighted.npy", bst.predict(X))

# dart binary
yb = (X[:, 0] + X[:, 2] > 0).astype(np.float64)
np.save(f"{OUT}/parity2_y_bin.npy", yb.astype(np.float32))
ds = ref_lgb.Dataset(X, label=yb, params={"verbose": -1, "max_bin": 63})
bst = ref_lgb.train({"objective": "binary", "boosting": "dart",
                     "num_leaves": 15, "drop_rate": 0.2, "verbose": -1,
                     "max_bin": 63, "deterministic": True,
                     "force_row_wise": True, "seed": 5, "drop_seed": 4},
                    ds, num_boost_round=12)
bst.save_model(f"{OUT}/ref_model_dart.txt")
np.save(f"{OUT}/ref_pred_dart.npy", bst.predict(X))

# lambdarank
n_q = 60
per_q = R // n_q
rel = (2.5 * X[:n_q * per_q, 0] + rng.rand(n_q * per_q)).astype(int)
rel = np.clip(rel - rel.min(), 0, 4)
grp = np.full(n_q, per_q)
np.save(f"{OUT}/parity2_rel.npy", rel.astype(np.float32))
np.save(f"{OUT}/parity2_grp.npy", grp.astype(np.int64))
ds = ref_lgb.Dataset(X[:n_q * per_q], label=rel, group=grp,
                     params={"verbose": -1, "max_bin": 63})
bst = ref_lgb.train({"objective": "lambdarank", "num_leaves": 15,
                     "verbose": -1, "max_bin": 63, "deterministic": True,
                     "force_row_wise": True, "seed": 5},
                    ds, num_boost_round=10)
bst.save_model(f"{OUT}/ref_model_rank.txt")
np.save(f"{OUT}/ref_pred_rank.npy", bst.predict(X[:n_q * per_q]))
print("fixtures2 written")


# ---- bagging-parity fixture: the per-iteration root internal_count is the
# exact in-bag row count, a direct observable of the reference's per-block
# LCG bagging streams (gbdt.cpp:192 BaggingHelper, utils/random.h)
params_bag = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
              "max_bin": 63, "min_data_in_leaf": 20, "verbose": -1,
              "deterministic": True, "force_row_wise": True, "seed": 7,
              "bagging_fraction": 0.6, "bagging_freq": 1, "bagging_seed": 5,
              "num_threads": 4}
Xb = np.load(f"{OUT}/parity_X.npy")[:, :5].astype(np.float64)  # numerical
yb = np.load(f"{OUT}/parity_y.npy").astype(np.float64)
dsb = ref_lgb.Dataset(Xb, label=yb, params={"max_bin": 63, "verbose": -1})
bb = ref_lgb.train(params_bag, dsb, num_boost_round=6)
bb.save_model(f"{OUT}/ref_model_bagging.txt")
dump = bb.dump_model()
root_counts = [t["tree_structure"].get("internal_count",
                                       t["tree_structure"].get("leaf_count"))
               for t in dump["tree_info"]]
np.save(f"{OUT}/ref_bag_root_counts.npy", np.asarray(root_counts, np.int64))
print("bagging root counts:", root_counts)
