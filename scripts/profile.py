"""On-chip profiling entry points, consolidated.

Three modes behind one documented wrapper (iter/micro used to live in
``profile_iter.py`` / ``profile_micro.py``, which drifted apart):

    # per-phase wall timing of one fused-engine boosting iteration,
    # driven through the product path on the attached chip
    BENCH_ROWS=2000000 python scripts/profile.py iter

    # micro-benchmarks of the primitives that bound GBDT training
    # (matmul/HBM/gather/sort/cumsum/Pallas histogram), each chained
    # inside ONE jit so the measurement is device throughput, not
    # dispatch latency
    python scripts/profile.py micro

    # parse a captured jax.profiler trace dir (a profile_dir config
    # window or a POST /profile capture) via obs/kernelstats.py and
    # print the top-K kernels by measured device time, joined to
    # their cost-ledger signatures when --telemetry points at the
    # run's JSONL — no TensorBoard needed (docs/Observability.md §15)
    python scripts/profile.py summarize /tmp/prof \
        [--telemetry run.jsonl] [--top 10] [--json]

For profiling a LIVE training job, the capture side is neither bench:
set ``metrics_port=<p>`` and ``POST /profile?iters=N`` against the
running process — the driver captures a bounded ``jax.profiler`` trace
at its next drain boundary without restarting the job
(docs/Observability.md §12), then ``summarize`` reads it back.
"""
from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ------------------------------------------------------------ iter mode
def _timed(label, fn, *a, **k):
    import jax
    t0 = time.perf_counter()
    out = fn(*a, **k)
    for x in jax.tree_util.tree_leaves(out):
        if hasattr(x, "block_until_ready"):
            x.block_until_ready()
    dt = time.perf_counter() - t0
    print(f"  {label:34s} {dt*1e3:9.1f} ms")
    return out


def main_iter() -> None:
    """Per-phase timing of one fused-engine boosting iteration on the
    attached chip (BENCH_ROWS scales the dataset)."""
    import numpy as np

    import jax.numpy as jnp

    import lightgbm_tpu as lgb

    n = int(os.environ.get("BENCH_ROWS", 2_000_000))
    rng = np.random.RandomState(0)
    X = rng.rand(n, 28).astype(np.float32)
    w = rng.randn(28).astype(np.float32)
    y = (X @ w + 0.5 * rng.randn(n) > 0).astype(np.float32)
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 1,
              "min_sum_hessian_in_leaf": 1e-3, "verbose": -1,
              "metric": "None", "tpu_engine": "fused"}
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    booster = lgb.Booster(params=params, train_set=ds)
    for _ in range(3):
        booster.update()  # warm all compiles

    g = booster._gbdt
    print(f"rows={n}")
    for rep in range(2):
        print(f"--- iter {rep}")
        t0_all = time.perf_counter()
        grad, hess = _timed("get_gradients", g._get_gradients)
        gh = _timed("gh stack", lambda: jnp.stack(
            [grad[0] * g.bag_weight, hess[0] * g.bag_weight,
             g.bag_weight], axis=1))
        from lightgbm_tpu.ops.fused_level import pack_gh, table_lookup
        fm = g._feature_mask()
        pad = g.fused_Rp - g.num_data
        gh_T = _timed("pack_gh+pad", lambda: pack_gh(
            jnp.pad(gh[:, 0], (0, pad)), jnp.pad(gh[:, 1], (0, pad)),
            jnp.pad(gh[:, 2], (0, pad)), g.fused_nch))
        fm_pad = jnp.zeros((g.fused_f_oh,), bool).at[:fm.shape[0]].set(fm)
        from lightgbm_tpu.models.frontier2 import grow_tree_fused
        tree, row_leaf = _timed("grow_tree_fused", lambda: grow_tree_fused(
            g.fused_bins_T, gh_T, g.fused_meta, fm_pad, g.params,
            g.max_leaves, g.fused_Bp, g.fused_f_oh, num_rows=g.num_data,
            nch=g.fused_nch, max_depth=int(g.config.max_depth),
            extra_levels=int(g.config.tpu_extra_levels),
            has_cat=g.has_cat, use_mono_bounds=g.use_mono_bounds,
            use_node_masks=g.use_node_masks,
            node_masks=g._node_masks_padded(),
            interpret=g.fused_interpret))
        _timed("int(num_leaves)", lambda: int(tree.num_leaves))
        ht, sf = _timed("to_host_tree", g._to_host_tree, tree,
                        g.shrinkage_rate)
        ht.apply_shrinkage(g.shrinkage_rate)
        lv_dev = jnp.asarray(ht.leaf_value, jnp.float32)
        delta = _timed("table_lookup", lambda: table_lookup(
            row_leaf[:g.num_data][None, :], lv_dev)[0])
        _timed("score add", lambda: g.scores.at[0].add(delta))
        print(f"  {'TOTAL':34s} "
              f"{(time.perf_counter()-t0_all)*1e3:9.1f} ms")


# ----------------------------------------------------------- micro mode
def _timeit(fn, *args, reps=3, warmup=1):
    import jax
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def _chain(body, n):
    """Run body n times sequentially inside one jit (data-dependent)."""
    import jax

    @jax.jit
    def run(*args):
        def step(i, carry):
            return body(i, carry, *args[1:])
        return jax.lax.fori_loop(0, n, step, args[0])
    return run


def main_micro() -> None:
    """Micro-benchmarks of the primitives that bound GBDT training on
    TPU, each chained N times inside ONE jit-compiled loop."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    R = 2_000_000
    Fp = 32
    B = 64
    N = 10
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, 63, size=(R, Fp)).astype(np.int32))
    bins_u8 = jnp.asarray(np.asarray(bins).astype(np.uint8))
    gh = jnp.asarray(rng.randn(R, 3).astype(np.float32))
    perm = jnp.asarray(rng.permutation(R).astype(np.int32))
    slot = jnp.asarray(rng.randint(0, 64, size=R).astype(np.int32))

    results = {}

    # 0. raw MXU throughput (chained, data-dependent)
    a = jnp.asarray(rng.randn(4096, 4096).astype(np.float32)).astype(
        jnp.bfloat16)
    f = _chain(lambda i, x, a: (x @ a), N)
    t = _timeit(f, a, a) / N
    results["matmul_4096_bf16_tflops"] = 2 * 4096**3 / t / 1e12

    # 1. HBM r/w bandwidth (chained adds)
    big = jnp.zeros((R, Fp), jnp.float32)
    f = _chain(lambda i, x: x + 1.0, N)
    t = _timeit(f, big) / N
    results["hbm_rw_f32_GBps"] = 2 * R * Fp * 4 / t / 1e9

    # 2. random row gather [R, Fp] uint8 (index fed by previous gather
    # so the chain cannot be elided)
    f = _chain(lambda i, p, x: (p + x[p][:, 0].astype(jnp.int32)) % R, N)
    t = _timeit(f, perm, bins_u8) / N
    results["row_gather_u8_ns_per_row"] = t / R * 1e9
    t = _timeit(f, perm, bins) / N
    results["row_gather_i32_ns_per_row"] = t / R * 1e9

    # 2b. 1-D gather / scatter
    f = _chain(lambda i, p, x: (p + x[p]) % R, N)
    t = _timeit(f, perm, slot) / N
    results["gather_1d_ns_per_elem"] = t / R * 1e9
    f = _chain(lambda i, p, x: (p + jnp.zeros_like(x).at[p].set(x)) % R,
               N)
    t = _timeit(f, perm, slot) / N
    results["scatter_1d_unique_ns_per_elem"] = t / R * 1e9

    # 3. sort (key,payload)
    f = _chain(lambda i, k, v: jax.lax.sort(((k * 7919 + 13) % R, v),
                                            num_keys=1)[0], N)
    t = _timeit(f, slot, perm) / N
    results["sort_kv_2M_ms"] = t * 1e3

    # 4. cumsum
    f = _chain(lambda i, x: jnp.cumsum(x) % 1000, N)
    t = _timeit(f, slot) / N
    results["cumsum_2M_ms"] = t * 1e3

    # 5. current pallas histogram, jit-compiled, per-pass
    from lightgbm_tpu.ops.pallas_histogram import \
        build_histograms_pallas_cm

    for S in (8, 64):
        @functools.partial(jax.jit, static_argnames=())
        def hist_loop(bins, gh, slot, _S=S):
            def step(i, acc):
                g, h, c = build_histograms_pallas_cm(
                    bins, gh, (slot + i) % _S, num_slots=_S, num_bins=B)
                return acc + g[0, 0, 0]
            return jax.lax.fori_loop(0, N, step, 0.0)
        t = _timeit(hist_loop, bins, gh, slot) / N
        results[f"pallas_hist_S{S}_ms"] = t * 1e3

    for k, v in results.items():
        print(f"{k:36s} {v if isinstance(v, str) else round(v, 3)}")


# ------------------------------------------------------- summarize mode
def main_summarize(argv) -> int:
    """Parse a profile dir (obs/kernelstats.py) and print the top-K
    kernels and per-executable measured device times, joined to
    cost-ledger signatures when a telemetry JSONL is given.  Host-side
    stdlib parsing only — runs anywhere, no TensorBoard."""
    import argparse
    import json

    from lightgbm_tpu.obs import kernelstats

    ap = argparse.ArgumentParser(
        prog="profile.py summarize",
        description="summarize a jax.profiler trace dir")
    ap.add_argument("dir", help="profile dir (the profile_dir config "
                                "window or POST /profile target)")
    ap.add_argument("--telemetry", default="",
                    help="telemetry_out JSONL of the same run — joins "
                         "kernels to cost/compile signatures")
    ap.add_argument("--top", type=int, default=10,
                    help="top-K kernels/executables to print")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full roofline record as JSON")
    args = ap.parse_args(argv)

    cost = compiles = None
    if args.telemetry:
        events = []
        with open(args.telemetry) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    pass
        cost, compiles = kernelstats.cost_entries_from_events(events)
    roof = kernelstats.roofline_from_dir(args.dir, cost_entries=cost,
                                         compile_entries=compiles,
                                         top=args.top)
    if args.as_json:
        print(json.dumps(roof, indent=1, sort_keys=True, default=str))
        return 0
    print(f"trace dir: {args.dir}")
    print(f"  files parsed: {roof['parsed_files']}/{roof['trace_files']}"
          f"  ({roof['trace_bytes']} bytes, "
          f"{roof['parse_errors']} errors)")
    print(f"  anchor dispatches: {roof['anchor_dispatches']}  "
          f"join coverage: {roof['join_coverage']:.3f}  "
          f"device time: {roof['total_device_time_us']:.1f} us "
          f"(+{roof['unattributed_time_us']:.1f} us unattributed)")
    for err in roof.get("errors", []):
        print(f"  ! {err}")
    if roof["executables"]:
        print("executables (by measured device time):")
    for ex in roof["executables"][:args.top]:
        sig = ex.get("signature") or f"<unjoined:{ex['kind']}>"
        per = ex.get("device_time_us_per_dispatch")
        frac = ex.get("measured_fraction")
        line = (f"  {sig:48s} {ex['device_time_us']:10.1f} us  "
                f"x{ex['dispatches']}")
        if per is not None:
            line += f"  {per:9.1f} us/disp"
        if frac is not None:
            line += f"  frac={frac:.3f}"
        if ex.get("achieved_flops_per_s") is not None:
            line += (f"  {ex['achieved_flops_per_s']:.3e} flop/s"
                     f"  {ex['achieved_bytes_per_s']:.3e} B/s")
        print(line)
        for k in ex.get("top_kernels", [])[:3]:
            print(f"      {k['name']:44s} {k['time_us']:10.1f} us  "
                  f"x{k['count']}")
    if roof["kernels"]:
        print("top kernels (all lanes):")
    for k in roof["kernels"][:args.top]:
        print(f"  {k['name']:48s} {k['time_us']:10.1f} us  "
              f"x{k['count']}")
    if not args.telemetry:
        print("(no --telemetry JSONL given: executables stay unjoined; "
              "pass the run's telemetry_out file to join signatures)")
    return 0


def main() -> int:
    mode = sys.argv[1] if len(sys.argv) > 1 else ""
    if mode == "iter":
        main_iter()
    elif mode == "micro":
        main_micro()
    elif mode == "summarize":
        return main_summarize(sys.argv[2:])
    else:
        print(__doc__)
        print("usage: python scripts/profile.py "
              "{iter|micro|summarize <dir>}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
