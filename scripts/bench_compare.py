"""Diff the two most recent bench records and flag perf regressions.

``bench.py`` appends every run's record (headline sec/iter + the TIMETAG
timer's phase totals) to ``BENCH_TRAJECTORY.jsonl``; this script compares
the latest record against the previous one and flags any phase — or the
headline — that got more than ``--threshold`` (default 15%) slower.
Per-phase comparison uses mean seconds per call (``total / count``) so
runs of different lengths (BENCH_ROWS / BENCH_ITERS smoke runs vs full
rounds) still diff meaningfully; phases whose per-call cost is under
``--min-seconds`` are skipped as noise.

Usage:
    python scripts/bench_compare.py [--trajectory PATH] [--threshold 0.15]
                                    [--min-seconds 0.005] [--fail-on-regress]

Besides the timing diffs, the DETERMINISTIC counters are gated when
both records carry them: ``dispatches_per_iter`` and its
eval/checkpoint/observability-leg twins (training fast-path eviction,
bench.py --micro), ``dispatches_per_request`` and
``compiles_per_1k_requests`` (serving bucketing/recompile regressions,
bench.py --serve) — these flag structural losses even on runners too
noisy for timing thresholds.

Prints one JSON report line; with ``--fail-on-regress`` exits 1 when any
regression was flagged (the CI smoke gate). Fewer than two comparable
records is a clean exit with ``"status": "insufficient_history"`` — the
first run of a fresh trajectory must not fail CI.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_DEFAULT_TRAJECTORY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_TRAJECTORY.jsonl")


def load_trajectory(path: str) -> List[Dict[str, Any]]:
    """Parse the JSONL trajectory, skipping corrupt lines (a crashed
    writer must not make the history unreadable)."""
    records: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return records
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"skipping corrupt trajectory line: {line[:80]}",
                      file=sys.stderr)
    # bench.py appends one line per emit and may emit the same run twice
    # (record, then record + failure tail): keep only each run's LAST
    # line, preserving first-seen order
    last: Dict[Any, Dict[str, Any]] = {}
    for i, r in enumerate(records):
        last[r.get("run_id", i)] = r
    return list(last.values())


def _per_call(phases: Dict[str, Any], name: str) -> Optional[float]:
    ent = phases.get(name)
    if not isinstance(ent, dict):
        return None
    total = float(ent.get("total", 0.0))
    count = int(ent.get("count", 0))
    if count <= 0:
        return None
    return total / count


def _ratio_entry(name: str, prev: float, cur: float,
                 threshold: float) -> Dict[str, Any]:
    ratio = cur / prev if prev > 0 else float("inf")
    return {"name": name, "prev": round(prev, 6), "cur": round(cur, 6),
            "ratio": round(ratio, 4),
            "regressed": ratio > 1.0 + threshold}


def compare(prev: Dict[str, Any], cur: Dict[str, Any],
            threshold: float = 0.15,
            min_seconds: float = 0.005,
            det_threshold: float = 0.25) -> Dict[str, Any]:
    """Build the comparison report: headline sec/iter plus every phase
    present in BOTH records (a phase that appears or disappears is
    reported informationally, not flagged — engine degradation changes
    the phase set legitimately)."""
    report: Dict[str, Any] = {
        "status": "ok",
        "prev_run": prev.get("run_id"),
        "cur_run": cur.get("run_id"),
        "threshold": threshold,
        "phases": [],
        "regressions": [],
    }
    pv, cv = prev.get("value"), cur.get("value")
    if isinstance(pv, (int, float)) and isinstance(cv, (int, float)) \
            and pv > 0:
        head = _ratio_entry(prev.get("metric", "headline"),
                            float(pv), float(cv), threshold)
        report["headline"] = head
        if head["regressed"]:
            report["regressions"].append(head)
    else:
        report["headline"] = None

    # deterministic-counter regressions (no wall-clock noise), so they
    # catch structural fast-path losses even on runners too noisy for
    # the timing thresholds:
    # - dispatches_per_iter (bench.py --micro): a training fast-path
    #   eviction — e.g. telemetry silently forcing the sync driver —
    #   moves it 0.125 -> 3+;
    # - eval_dispatches_per_iter (bench.py --micro eval leg): the
    #   eval-enabled config (valid sets + early stopping + logging
    #   callbacks) regressing off the on-device-eval megastep back to
    #   per-iteration sync evaluation moves it from ~1/chunk to >= 3;
    # - dispatches_per_request (bench.py --serve): a serving bucketing/
    #   chunking regression moves it off exactly 1.0;
    # - compiles_per_1k_requests (bench.py --serve): a bucket-shape leak
    #   recompiling per request size moves it off 0. Zero-to-zero
    #   compares clean; zero-to-nonzero always flags (the ratio has no
    #   finite baseline).
    # These counters carry NO wall-clock noise, so they get their own
    # tight ``det_threshold`` (default 25%) instead of the deliberately
    # huge timing threshold the CI smoke gates pass — a 2x
    # dispatches_per_request regression must fail even under
    # --threshold 9.0.
    # - ckpt_dispatches_per_iter (bench.py --micro checkpoint leg): the
    #   same training with async checkpointing armed — resilience
    #   checkpoints capture at drain boundaries off the dispatch path,
    #   so this must EQUAL dispatches_per_iter; drift means
    #   checkpointing started evicting the fast path.
    # - obs_dispatches_per_iter (bench.py --micro observability leg):
    #   the same training with the live OpenMetrics exporter serving
    #   scrapes — the observability plane reads registry snapshots off
    #   the device path, so this too must EQUAL dispatches_per_iter.
    # - ingest_dispatches_per_iter (bench.py --micro ingest leg): the
    #   same training fed by chunked streaming ingest + the binary
    #   cache — a data-loading plane that must not touch the fast
    #   path, so this must EQUAL dispatches_per_iter;
    # - ingest_chunks / ingest_max_live_chunks: the chunked pipeline's
    #   deterministic chunk arithmetic and its bounded-host-residency
    #   invariant (<= 2) — a buffering regression moves either;
    # - ingest_model_mismatch: 0.0 while the streamed/cached model
    #   serializes byte-equal to the monolithic text load (the ingest
    #   bit-identity contract); zero-to-nonzero always flags.
    # - mp_dispatches_per_iter (bench.py --micro multiproc leg): the
    #   2-process megastep over the gloo mesh — the multi-chip fast
    #   path pays EXACTLY the single-device dispatch schedule
    #   (mp_dispatches_per_iter == dispatches_per_iter, 0.125 at
    #   defaults); an eviction back to the per-iteration sync driver
    #   moves it to >= 3.
    # - shed_ratio / reject_ratio (bench.py --serve overload leg): the
    #   gated open-loop overload makes both near-exact by construction
    #   (the queue fills to its request bound, the gate outlasts every
    #   queued deadline) — a drift means admission control or deadline
    #   shedding changed shape;
    # - overload_unresolved / overload_queue_overflow: MUST stay 0 —
    #   an unresolved future is a leak, a queue past its bound is the
    #   unbounded-backlog failure this whole plane exists to prevent;
    #   zero-to-nonzero always flags;
    # - rollover_dropped_requests: MUST stay 0 — the atomic-swap
    #   rollover contract (continuous traffic, zero dropped);
    #   zero-to-nonzero always flags.
    # - hist_dispatches_per_iter (bench.py --micro histogram leg): the
    #   three histogram-plane cuts (quantized gradients, gain
    #   screening, adaptive bins) riding the megastep — must EQUAL
    #   dispatches_per_iter; drift means a cut started evicting it;
    # - ctl_dispatches_per_iter (bench.py --micro control-plane leg):
    #   training with the metrics exporter up and a LIVE
    #   POST /profile?iters=N captured mid-run — the on-demand
    #   profiling window opens/closes at drain boundaries, so this
    #   must EQUAL dispatches_per_iter exactly (profiling is
    #   dispatch-neutral); drift means the control plane started
    #   paying device round trips;
    # - ctl_profile_windows: closed on-demand windows in that leg —
    #   exactly 1; 0 means the endpoint stopped firing (the
    #   neutrality equality would then pass vacuously);
    # - hist_bytes_per_iter / hist_bytes_per_iter_f32: the analytic
    #   byte model of the histogram plane under the cut / baseline
    #   layouts (pure layout arithmetic — zero wall-clock noise); an
    #   increase means the packing or quantized channel layout
    #   regressed;
    # - hist_quant_bits / screening_active_features: the active cut
    #   configuration and the screening mask width — shape drifts
    #   flag.
    # - drift_dispatches_per_iter (bench.py --micro drift leg):
    #   training with data-profile capture on — the profile is pure
    #   host numpy at dataset finalize, so this must EQUAL
    #   dispatches_per_iter;
    # - serve_drift_dispatches_per_request /
    #   serve_drift_compiles_per_1k: the closed loop with the serving
    #   DriftMonitor evaluating — accumulation rides the already-
    #   encoded batch host-side, so exactly 1.0 / 0 like the bare
    #   serving contract; an increase means drift monitoring started
    #   paying device round trips or recompiles;
    # - drift_alerts / drift_alerts_control: EXACTLY one hysteresis-
    #   gated alert on the deterministically shifted feed, zero on the
    #   in-distribution control (zero-to-nonzero always flags);
    # - drift_psi_max: the shifted feed's PSI against the embedded
    #   training profile — fixed seeds + integer bin counts make it
    #   exactly reproducible, so any movement means the profile or
    #   divergence arithmetic changed shape.
    # - fleet_dispatches_per_request_worst /
    #   fleet_compiles_per_1k_worst (bench.py --serve fleet leg): the
    #   PER-DEVICE deterministic serving contract — the device farthest
    #   off 1.0 dispatches/request and the worst per-device compile
    #   rate; a routing or per-replica-warmup regression moves them;
    # - fleet_unrouted_devices: devices the closed-loop round-robin
    #   tie-break never routed — MUST stay 0 (a device the fleet pays
    #   residency for but never serves from); zero-to-nonzero flags;
    # - bulk_identity_mismatch: 0.0 while predict_bulk (row-sharded
    #   over the mesh) stays numerically identical to the
    #   single-device dispatch path; zero-to-nonzero always flags.
    # - slo_dispatches_per_iter (bench.py --micro slo leg): training
    #   with the SLO engine armed on the built-in catalog — burn-rate
    #   evaluation reads host-side telemetry snapshots only, so this
    #   must EQUAL dispatches_per_iter exactly;
    # - slo_alerts (same leg): alerts fired on a HEALTHY run — the
    #   false-positive gate, MUST stay 0; zero-to-nonzero always flags;
    # - slo_dispatches_per_request (bench.py --serve forced-alert leg):
    #   the closed loop with the SLO engine armed — exactly 1.0 like
    #   the bare serving contract;
    # - slo_false_positives / slo_alert_missed / slo_alert_unresolved /
    #   slo_incident_invalid (same leg): the deterministic alert
    #   lifecycle — the injected slow dispatch must fire EXACTLY the
    #   latency objective (no other objective fires), exactly once,
    #   resolve after the ring refills, and leave a schema-valid
    #   incident artifact; each is 0 on a correct run and
    #   zero-to-nonzero always flags;
    # - roofline_join_coverage (bench.py --micro control-plane leg):
    #   dispatch-weighted fraction of measured profile-window anchors
    #   that joined an analytic cost signature (obs/kernelstats.py) —
    #   EXACTLY 1.0 on a correct run; a DROP means a signature stopped
    #   joining, so this one flags decreases (rising coverage is fine);
    # - roofline_dispatches_per_iter (same leg): the dispatch counter
    #   measured WITH the trace parse active — must equal
    #   dispatches_per_iter (the parser is host-side, dispatch-neutral);
    # - perfdb_samples (same leg): measured samples accumulated for the
    #   most-sampled shape key across the leg's two profiled runs —
    #   exactly 2 (one per run); a drop means cross-run accumulation
    #   in the perf database (obs/perfdb.py) broke, flags decreases.
    report["deterministic"] = {}
    _decrease_only = ("roofline_join_coverage", "perfdb_samples")
    for name in ("dispatches_per_iter", "eval_dispatches_per_iter",
                 "ckpt_dispatches_per_iter", "obs_dispatches_per_iter",
                 "ingest_dispatches_per_iter", "ingest_chunks",
                 "ingest_max_live_chunks", "ingest_model_mismatch",
                 "mp_dispatches_per_iter",
                 "ctl_dispatches_per_iter", "ctl_profile_windows",
                 "hist_dispatches_per_iter", "hist_bytes_per_iter",
                 "hist_bytes_per_iter_f32", "hist_quant_bits",
                 "screening_active_features",
                 "dispatches_per_request", "compiles_per_1k_requests",
                 "shed_ratio", "reject_ratio", "overload_unresolved",
                 "overload_queue_overflow",
                 "rollover_dropped_requests",
                 "drift_dispatches_per_iter",
                 "serve_drift_dispatches_per_request",
                 "serve_drift_compiles_per_1k", "drift_alerts",
                 "drift_alerts_control", "drift_psi_max",
                 "fleet_dispatches_per_request_worst",
                 "fleet_compiles_per_1k_worst",
                 "fleet_unrouted_devices", "bulk_identity_mismatch",
                 "slo_dispatches_per_iter", "slo_alerts",
                 "slo_dispatches_per_request", "slo_false_positives",
                 "slo_alert_missed", "slo_alert_unresolved",
                 "slo_incident_invalid",
                 "roofline_join_coverage",
                 "roofline_dispatches_per_iter", "perfdb_samples"):
        p, c = prev.get(name), cur.get(name)
        if not (isinstance(p, (int, float)) and isinstance(c, (int, float))):
            continue
        if p <= 0:
            # ratio has no finite baseline; None keeps the report
            # strict-JSON (float('inf') would serialize as the
            # non-standard token Infinity)
            ent = {"name": name, "prev": round(float(p), 6),
                   "cur": round(float(c), 6),
                   "ratio": None if c > 0 else 1.0,
                   "regressed": c > 0}
        else:
            ent = _ratio_entry(name, float(p), float(c),
                               min(threshold, det_threshold))
            if name in _decrease_only:
                # more-is-better counters: only a DROP regresses
                ent["regressed"] = float(c) < float(p) * (
                    1.0 - min(threshold, det_threshold))
        report["deterministic"][name] = ent
        if ent["regressed"]:
            report["regressions"].append(ent)
    # back-compat view the perf-smoke CI assertion reads
    report["dispatches"] = report["deterministic"].get(
        "dispatches_per_iter")

    prev_ph = prev.get("phase_timings") or {}
    cur_ph = cur.get("phase_timings") or {}
    for name in sorted(set(prev_ph) & set(cur_ph)):
        p, c = _per_call(prev_ph, name), _per_call(cur_ph, name)
        if p is None or c is None or max(p, c) < min_seconds:
            continue
        ent = _ratio_entry(name, p, c, threshold)
        report["phases"].append(ent)
        if ent["regressed"]:
            report["regressions"].append(ent)
    report["only_prev"] = sorted(set(prev_ph) - set(cur_ph))
    report["only_cur"] = sorted(set(cur_ph) - set(prev_ph))
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trajectory", default=os.environ.get(
        "BENCH_TRAJECTORY", _DEFAULT_TRAJECTORY))
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="fractional slowdown that counts as a "
                         "regression (0.15 = 15%%)")
    ap.add_argument("--min-seconds", type=float, default=0.005,
                    help="ignore phases cheaper than this per call")
    ap.add_argument("--det-threshold", type=float, default=0.25,
                    help="separate (tight) threshold for the "
                         "deterministic counters — they carry no "
                         "wall-clock noise, so the huge timing "
                         "thresholds the smoke gates use must not "
                         "loosen them")
    ap.add_argument("--fail-on-regress", action="store_true",
                    help="exit 1 when a regression is flagged")
    args = ap.parse_args(argv)

    records = load_trajectory(args.trajectory)
    # only records that measured the headline are comparable — failure
    # records (probe failures, watchdog kills) carry value=None, and
    # their phase_timings cover a truncated run that would diff as
    # spurious regressions against a complete one
    measured = [r for r in records
                if isinstance(r.get("value"), (int, float))]
    if len(measured) < 2:
        print(json.dumps({"status": "insufficient_history",
                          "records": len(records),
                          "measured": len(measured),
                          "trajectory": args.trajectory}))
        return 0

    # diff like-for-like only: the latest record against the most recent
    # prior record with the SAME bench_config (rows/iters) — a smoke run
    # next to a full run differs by orders of magnitude in per-phase
    # cost and would flag fake regressions
    cur = measured[-1]
    prev = next((r for r in reversed(measured[:-1])
                 if r.get("bench_config") == cur.get("bench_config")),
                None)
    if prev is None:
        print(json.dumps({"status": "insufficient_history",
                          "reason": "no prior record with matching "
                                    "bench_config",
                          "cur_config": cur.get("bench_config"),
                          "measured": len(measured),
                          "trajectory": args.trajectory}))
        return 0

    report = compare(prev, cur,
                     threshold=args.threshold,
                     min_seconds=args.min_seconds,
                     det_threshold=args.det_threshold)
    print(json.dumps(report))
    for ent in report["regressions"]:
        pct = "from-zero" if ent.get("ratio") is None \
            else f"{(ent['ratio'] - 1) * 100:.1f}% slower"
        print(f"REGRESSION {ent['name']}: {ent['prev']} -> {ent['cur']} "
              f"({pct})", file=sys.stderr)
    if report["regressions"] and args.fail_on_regress:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
