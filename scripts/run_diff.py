"""Diff two consolidated run reports and flag regressions.

The training/serving drivers emit one schema-versioned
``run_report.json`` per run (``run_report_out=<path>`` at finalize, or
live from ``GET /report`` on the metrics exporter).  This tool is the
A/B half of that artifact: compare a candidate run against a baseline
with the deterministic-counter strictness ``scripts/bench_compare.py``
established — counters that carry no wall-clock noise (dispatches per
iteration, cost-ledger flops/bytes per iteration, the analytic-model
fraction) get a tight threshold, zero-to-nonzero always flags, a NEW
``megastep_evicted`` / ``degrade`` reason (or ``drift_alert``) always
flags, an SLO objective that FIRED in the candidate but not in the
baseline (``slo_alert:<objective>``) always flags — baseline-clean vs
candidate-firing exits 1 under ``--fail-on-regress`` with no
threshold — and wall timings diff per-call under the loose timing
threshold — flagged timings are informational unless
``--fail-on-timing`` is given, because identical runs must compare
clean and per-call wall time between identical runs crosses any
usable threshold on scheduler noise alone.

The roofline plane rides the same gate two ways: report-vs-report, the
candidate's MEASURED per-executable device time per dispatch
(``roofline`` report section, obs/kernelstats.py) diffs against the
baseline's under the loose threshold and joins the hard regressions;
and ``--perf-db <path>`` additionally checks the candidate against the
accumulated measured history in the shape-keyed perf database
(obs/perfdb.py) — a signature whose measured time slipped past the
threshold vs its db mean flags even when the baseline report predates
the roofline section.

Usage:
    python scripts/run_diff.py baseline.json candidate.json \
        [--threshold 0.15] [--det-threshold 0.05] [--fail-on-regress] \
        [--perf-db perf.jsonl]

Exit codes: 0 clean (identical runs compare clean by construction),
1 regressions flagged under ``--fail-on-regress``, 2 the reports are
not comparable (schema mismatch / unreadable).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("baseline", help="baseline run_report.json")
    ap.add_argument("candidate", help="candidate run_report.json")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="fractional per-call slowdown that counts as "
                         "a timing regression (0.15 = 15%%)")
    ap.add_argument("--det-threshold", type=float, default=0.05,
                    help="tight threshold for the deterministic "
                         "counters (no wall-clock noise)")
    ap.add_argument("--fail-on-regress", action="store_true",
                    help="exit 1 when a regression is flagged")
    ap.add_argument("--perf-db", default="", dest="perf_db",
                    help="shape-keyed perf database (obs/perfdb.py "
                         "JSONL): also flag candidate roofline "
                         "executables whose measured device time per "
                         "dispatch regressed past --threshold vs "
                         "their accumulated db mean")
    ap.add_argument("--fail-on-timing", action="store_true",
                    help="let flagged wall-timing swings fail the run "
                         "too (off by default: scheduler noise between "
                         "identical runs crosses the timing threshold; "
                         "the deterministic counters are the gate)")
    args = ap.parse_args(argv)

    from lightgbm_tpu.obs.report import compare_reports, load_report
    try:
        prev = load_report(args.baseline)
        cur = load_report(args.candidate)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(json.dumps({"status": "unreadable", "error": str(e)}))
        return 2

    rep = compare_reports(prev, cur, threshold=args.threshold,
                          det_threshold=args.det_threshold,
                          fail_on_timing=args.fail_on_timing)
    if args.perf_db:
        # measured-history gate: each candidate roofline executable vs
        # the mean of its accumulated perfdb samples for the same
        # signature — catches a slow drift no single baseline report
        # would show
        from lightgbm_tpu.obs import perfdb
        db_rows = perfdb.PerfDB(args.perf_db).load()["rows"]
        rep.setdefault("perf_db", [])
        for ex in (cur.get("roofline", {}) or {}).get(
                "executables", []) or []:
            sig = ex.get("signature")
            per = ex.get("device_time_us_per_dispatch")
            if not sig or not isinstance(per, (int, float)) or per <= 0:
                continue
            hist = [float(r["device_time_us_per_dispatch"])
                    for r in db_rows
                    if (r.get("key", {}) or {}).get("signature") == sig
                    and isinstance(r.get("device_time_us_per_dispatch"),
                                   (int, float))]
            if not hist:
                continue
            base = sum(hist) / len(hist)
            ratio = float(per) / base if base > 0 else None
            ent = {"name": f"perfdb:{sig}", "prev": round(base, 3),
                   "cur": round(float(per), 3),
                   "ratio": round(ratio, 4) if ratio else None,
                   "samples": len(hist),
                   "regressed": bool(ratio
                                     and ratio > 1.0 + args.threshold)}
            rep["perf_db"].append(ent)
            if ent["regressed"]:
                rep["regressions"].append(ent)
    print(json.dumps(rep))
    if rep["status"] != "ok":
        print(f"run_diff: not comparable ({rep['status']})",
              file=sys.stderr)
        return 2
    for ent in rep["regressions"]:
        pct = "from-zero/new" if ent.get("ratio") is None \
            else f"ratio {ent['ratio']}"
        print(f"REGRESSION {ent['name']}: {ent['prev']} -> "
              f"{ent['cur']} ({pct})", file=sys.stderr)
    in_regress = {id(e) for e in rep["regressions"]}
    for ent in rep["timings"]:
        if ent["regressed"] and id(ent) not in in_regress:
            print(f"TIMING (info) {ent['name']}: {ent['prev']} -> "
                  f"{ent['cur']} (ratio {ent['ratio']})",
                  file=sys.stderr)
    if rep["regressions"] and args.fail_on_regress:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
