"""Per-phase timing of one fused-engine boosting iteration on the attached
chip. Run: BENCH_ROWS=2000000 python scripts/profile_iter.py"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb


def t(label, fn, *a, **k):
    t0 = time.perf_counter()
    out = fn(*a, **k)
    out_flat = jax.tree_util.tree_leaves(out)
    for x in out_flat:
        if hasattr(x, "block_until_ready"):
            x.block_until_ready()
    dt = time.perf_counter() - t0
    print(f"  {label:34s} {dt*1e3:9.1f} ms")
    return out


def main():
    n = int(os.environ.get("BENCH_ROWS", 2_000_000))
    rng = np.random.RandomState(0)
    X = rng.rand(n, 28).astype(np.float32)
    w = rng.randn(28).astype(np.float32)
    y = (X @ w + 0.5 * rng.randn(n) > 0).astype(np.float32)
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 1,
              "min_sum_hessian_in_leaf": 1e-3, "verbose": -1,
              "metric": "None", "tpu_engine": "fused"}
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    booster = lgb.Booster(params=params, train_set=ds)
    for _ in range(3):
        booster.update()  # warm all compiles

    g = booster._gbdt
    print(f"rows={n}")
    for rep in range(2):
        print(f"--- iter {rep}")
        t0_all = time.perf_counter()
        grad, hess = t("get_gradients", g._get_gradients)
        gh = t("gh stack", lambda: jnp.stack(
            [grad[0] * g.bag_weight, hess[0] * g.bag_weight, g.bag_weight],
            axis=1))
        from lightgbm_tpu.ops.fused_level import pack_gh, table_lookup
        fm = g._feature_mask()
        pad = g.fused_Rp - g.num_data
        gh_T = t("pack_gh+pad", lambda: pack_gh(
            jnp.pad(gh[:, 0], (0, pad)), jnp.pad(gh[:, 1], (0, pad)),
            jnp.pad(gh[:, 2], (0, pad)), g.fused_nch))
        fm_pad = jnp.zeros((g.fused_f_oh,), bool).at[:fm.shape[0]].set(fm)
        from lightgbm_tpu.models.frontier2 import grow_tree_fused
        tree, row_leaf = t("grow_tree_fused", lambda: grow_tree_fused(
            g.fused_bins_T, gh_T, g.fused_meta, fm_pad, g.params,
            g.max_leaves, g.fused_Bp, g.fused_f_oh, num_rows=g.num_data,
            nch=g.fused_nch, max_depth=int(g.config.max_depth),
            extra_levels=int(g.config.tpu_extra_levels),
            has_cat=g.has_cat, use_mono_bounds=g.use_mono_bounds,
            use_node_masks=g.use_node_masks,
            node_masks=g._node_masks_padded(),
            interpret=g.fused_interpret))
        t("int(num_leaves)", lambda: int(tree.num_leaves))
        ht, sf = t("to_host_tree", g._to_host_tree, tree, g.shrinkage_rate)
        ht.apply_shrinkage(g.shrinkage_rate)
        lv_dev = jnp.asarray(ht.leaf_value, jnp.float32)
        delta = t("table_lookup", lambda: table_lookup(
            row_leaf[:g.num_data][None, :], lv_dev)[0])
        t("score add", lambda: g.scores.at[0].add(delta))
        print(f"  {'TOTAL':34s} {(time.perf_counter()-t0_all)*1e3:9.1f} ms")


if __name__ == "__main__":
    main()
