"""Tile-width x quant-bits ablation of the fused histogram level pass.

The histogram-plane cuts land with their CPU-side contracts proven
(byte-identity, accuracy A/Bs, dispatch parity) but their on-chip speed
unmeasured — the chip tunnel has been down since r03.  This harness is
the ready-to-run measurement for when it returns: it times
``ops/fused_level.level_pass`` over a tile-width x quant-bits grid
(f32/bf16x2 baseline vs int16 vs int8 channels, padded vs adaptive
layout) and appends one tagged record per combination to
BENCH_TRAJECTORY.jsonl, so the ablation series lands in the same history
``scripts/bench_compare.py`` reads.

Run (on the chip):   ROWS=10500000 python scripts/ablate_hist.py
CPU smoke:           ROWS=4096 INTERPRET=1 REPS=1 python scripts/ablate_hist.py
Knobs: TILES=0,512,1024,2048  BITS=0,16,8  SP=64  MIXED=1 (half the
features at 8 distinct values — the adaptive-layout shape).

PERF_DB=<path> additionally appends each measured combination to the
shape-keyed performance database (obs/perfdb.py) — the same store the
profile-window close hook and ``bench.py`` write, so the ablation grid
lands in the history ``scripts/perfdb_query.py`` and
``scripts/run_diff.py --perf-db`` read.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

if os.environ.get("INTERPRET"):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.ops import fused_level as fl  # noqa: E402
from lightgbm_tpu.ops.layout import (hist_plane_bytes,  # noqa: E402
                                     packed_feature_layout)
from lightgbm_tpu.ops.quantize import QNCH  # noqa: E402

_TRAJECTORY = os.environ.get(
    "BENCH_TRAJECTORY",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "BENCH_TRAJECTORY.jsonl"))
_RUN_ID = f"{time.strftime('%Y%m%dT%H%M%S')}_{os.getpid()}_ablate_hist"


def _append(rec):
    rec = dict(rec, metric="ablate_hist", run_id=_RUN_ID,
               ts=round(time.time(), 3))
    try:
        with open(_TRAJECTORY, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
    except Exception as e:  # the ablation must never lose a timing
        print(f"trajectory append failed: {e}", file=sys.stderr)
    print(json.dumps(rec), flush=True)


def main():
    R = int(os.environ.get("ROWS", 10_500_000))
    reps = int(os.environ.get("REPS", 5))
    Sp = int(os.environ.get("SP", 64))
    interpret = bool(os.environ.get("INTERPRET"))
    mixed = os.environ.get("MIXED", "1") != "0"
    n_feat = int(os.environ.get("FEATURES", 28))
    max_bin = int(os.environ.get("MAX_BIN", 63))
    tiles = [int(t) for t in os.environ.get("TILES",
                                            "0,512,1024,2048").split(",")]
    bits_list = [int(b) for b in os.environ.get("BITS", "0,16,8").split(",")]

    F_oh, Bp = fl.feature_layout(n_feat, max_bin)
    Rp = ((R + 2047) // 2048) * 2048
    rng = np.random.RandomState(0)
    num_bin = np.full(n_feat, max_bin, np.int32)
    if mixed:
        num_bin[n_feat // 2:] = 9        # 8 distinct values + missing bin
    bins_np = np.stack([rng.randint(0, nb, Rp) for nb in num_bin]) \
        .astype(np.int8)
    Fp = max(F_oh, 8)
    bins_full = np.zeros((Fp, Rp), np.int8)
    bins_full[:n_feat] = bins_np
    leaf_T = jnp.zeros((1, Rp), jnp.int32)
    g = rng.randn(Rp).astype(np.float32)
    h = np.abs(rng.randn(Rp)).astype(np.float32)
    ones = np.ones(Rp, np.float32)

    layouts = [("padded", None)]
    pk = packed_feature_layout(num_bin, max_bin, f_oh=F_oh)
    if pk.fb < F_oh * Bp:
        layouts.append(("packed", pk))

    tbl = (jnp.zeros((Sp, 128), jnp.int32)
           .at[:, 0].set(-2).at[0, 0].set(0).at[0, 2].set(1))
    print(f"rows={R} (padded {Rp}) F_oh={F_oh} Bp={Bp} Sp={Sp} "
          f"packed_fb={pk.fb}", file=sys.stderr)

    for lname, packed in layouts:
        if packed is not None:
            order = np.asarray(packed.feat_order)
            bt = np.zeros((Fp, Rp), np.int8)
            bt[:n_feat] = bins_np[order]
            bins_T = jnp.asarray(bt)
            fb = packed.fb
        else:
            bins_T = jnp.asarray(bins_full)
            fb = F_oh * Bp
        for bits in bits_list:
            if bits:
                gh_T, scales = fl.pack_gh_quant(
                    jnp.asarray(g), jnp.asarray(h), jnp.asarray(ones),
                    bits, np.uint32(1))
                nch = QNCH[bits]
            else:
                gh_T = fl.pack_gh(jnp.asarray(g), jnp.asarray(h),
                                  jnp.asarray(ones), 5)
                nch = 5
            w0 = packed.widths[0] if packed is not None else Bp
            W = jnp.zeros((Sp, fb), jnp.bfloat16).at[0, :w0].set(1)
            for tile in tiles:
                def one(lt):
                    return fl.level_pass(
                        bins_T, lt, gh_T, W, tbl, num_slots=Sp,
                        num_bins=Bp, f_oh=F_oh, nch=nch,
                        tile_rows=tile, interpret=interpret,
                        quant_bits=bits, packed=packed)
                try:
                    hst, nl = one(leaf_T)
                    float(jnp.sum(hst))            # compile + settle
                    t0 = time.perf_counter()
                    lt = leaf_T
                    for _ in range(reps):
                        hst, lt = one(lt)
                    float(jnp.sum(hst))
                    sec = (time.perf_counter() - t0) / reps
                except Exception as e:
                    _append({"layout": lname, "bits": bits, "tile": tile,
                             "error": f"{type(e).__name__}: {e}"[:200]})
                    continue
                eff_tile = tile or fl.default_tile_rows(
                    Sp, F_oh * Bp, nch, wide_bins=Bp > 256)
                _append({
                    "layout": lname, "bits": bits, "tile": tile,
                    "value": round(sec, 6), "unit": "s/pass",
                    "rows": R, "sp": Sp, "fb": fb, "nch": nch,
                    "interpret": interpret,
                    "bytes_per_level": hist_plane_bytes(
                        fb, nch, Sp, Rp, min(eff_tile, Rp), bits),
                    "rows_per_s": round(R / sec, 1),
                })
                if os.environ.get("PERF_DB"):
                    # one measured sample per combination in the
                    # shape-keyed perf database (obs/perfdb.py):
                    # level_pass timing keyed exactly like the
                    # training executables, tile width in the
                    # signature so the grid stays queryable
                    from lightgbm_tpu.obs import perfdb
                    key = perfdb.make_key(
                        f"level_pass[sp={Sp},tile={tile}]",
                        "hist_level",
                        f"r{Rp}.f{n_feat}.b{max_bin}",
                        jax.default_backend(), quant_bits=bits,
                        packed_layout=packed is not None)
                    perfdb.PerfDB(os.environ["PERF_DB"]).append([
                        perfdb.sample(
                            key, dispatches=reps,
                            device_time_us_per_dispatch=sec * 1e6,
                            achieved_bytes_per_s=hist_plane_bytes(
                                fb, nch, Sp, Rp,
                                min(eff_tile, Rp), bits) / sec,
                            source="ablate_hist", run_id=_RUN_ID)])


if __name__ == "__main__":
    main()
