"""Query the shape-keyed performance database (obs/perfdb.py).

The perfdb is the append-only JSONL of measured per-executable device
times that profile-window closes, ``bench.py`` and
``scripts/ablate_hist.py`` accumulate (``perf_db=<path>``).  This CLI
is the read side an operator (or the item-5 autotuner, interactively)
uses:

    # per-key summaries: sample counts, mean/min/max measured device
    # time per dispatch, best achieved rates
    python scripts/perfdb_query.py perf.jsonl

    # filter by key fields — full signature, its pre-'[' base, kind,
    # shape class, backend, quant bits, or a specific key_id
    python scripts/perfdb_query.py perf.jsonl --kind megastep \
        --backend cpu --shape-class r1024.f6.b63

    # raw matching rows instead of summaries (newest last), as JSON
    python scripts/perfdb_query.py perf.jsonl --rows --json

Exit status 1 when nothing matches, so shell pipelines can gate on
"do we have a measured baseline for this shape yet".
docs/Observability.md §15 documents the row schema.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu.obs import perfdb  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="query the shape-keyed perf database "
                    "(obs/perfdb.py JSONL)")
    ap.add_argument("path", help="perf database file (perf_db=<path>)")
    ap.add_argument("--signature", default="",
                    help="full signature or its pre-'[' base "
                         "(e.g. 'megastep')")
    ap.add_argument("--kind", default="",
                    help="executable kind (megastep/fast_step/"
                         "serve_bucket)")
    ap.add_argument("--shape-class", default="", dest="shape_class")
    ap.add_argument("--backend", default="")
    ap.add_argument("--quant-bits", default="", dest="quant_bits")
    ap.add_argument("--key-id", default="", dest="key_id")
    ap.add_argument("--source", default="",
                    help="writer tag (profile_window/bench/"
                         "ablate_hist)")
    ap.add_argument("--rows", action="store_true",
                    help="print matching rows instead of per-key "
                         "summaries")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    db = perfdb.PerfDB(args.path)
    loaded = db.load()
    rows = db.query(loaded["rows"], signature=args.signature,
                    kind=args.kind, shape_class=args.shape_class,
                    backend=args.backend, quant_bits=args.quant_bits,
                    key_id=args.key_id, source=args.source)
    if args.rows:
        if args.as_json:
            print(json.dumps(rows, indent=1, sort_keys=True,
                             default=str))
        else:
            for row in rows:
                key = row.get("key", {})
                print(f"{row.get('key_id', '?')} "
                      f"{key.get('signature', '?'):48s} "
                      f"{row.get('device_time_us_per_dispatch', 0):10.3f}"
                      f" us/disp  x{row.get('dispatches', 0)}  "
                      f"[{row.get('source', '?')}]")
    else:
        summaries = perfdb.summarize(rows)
        if args.as_json:
            print(json.dumps(summaries, indent=1, sort_keys=True,
                             default=str))
        else:
            print(f"{len(loaded['rows'])} rows "
                  f"({loaded['skipped']} skipped), "
                  f"{len(rows)} matching, "
                  f"{len(summaries)} keys")
            for ent in summaries:
                key = ent.get("key", {})
                t = ent.get("device_time_us_per_dispatch", {})
                line = (f"  {ent['key_id']} "
                        f"{key.get('signature', '?'):44s} "
                        f"[{key.get('kind', '?')},"
                        f"{key.get('shape_class', '?')},"
                        f"{key.get('backend', '?')},"
                        f"q{key.get('quant_bits', 0)},"
                        f"w{key.get('world_size', 1)}] "
                        f"n={ent['samples']}")
                if t:
                    line += (f"  {t['mean']:.3f} us/disp "
                             f"(min {t['min']:.3f}, max {t['max']:.3f}, "
                             f"last {t['last']:.3f})")
                if ent.get("achieved_flops_per_s_best") is not None:
                    line += (f"  best "
                             f"{ent['achieved_flops_per_s_best']:.3e} "
                             f"flop/s")
                print(line)
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
