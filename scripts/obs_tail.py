"""Tail / summarize a telemetry JSONL stream without hand-parsing.

The observability sinks are all JSONL (``telemetry_out`` event streams,
``BENCH_TRAJECTORY.jsonl`` bench records); operators keep re-deriving
the same jq incantations to answer "what happened".  This tool is the
shared reader:

    # one line per event, human-ordered fields
    python scripts/obs_tail.py run.jsonl

    # only what matters right now
    python scripts/obs_tail.py run.jsonl --event anomaly,straggler
    python scripts/obs_tail.py run.jsonl.rank1 --rank 1 --last 20

    # per-event counts, iteration span, findings (plus cost:/hist:/
    # drift: lines when the run emitted cost_ledger/drift records)
    python scripts/obs_tail.py run.jsonl --summary

    # render a consolidated run report (run_report_out / GET /report)
    python scripts/obs_tail.py --report run_report.json

    # live: keep printing as the training run appends
    python scripts/obs_tail.py run.jsonl --follow

    # bench trajectory: dedup re-emitted records by run_id with the
    # same last-wins reader bench_compare uses
    python scripts/obs_tail.py BENCH_TRAJECTORY.jsonl --dedup-runs

Corrupt lines are skipped with a note (a crashed writer must not make
the stream unreadable), matching ``bench_compare.load_trajectory``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Iterator, List, Optional

_HEAD_KEYS = ("event", "iter", "rank")


def _parse_lines(lines) -> Iterator[Dict[str, Any]]:
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            print(f"skipping corrupt line: {line[:80]}", file=sys.stderr)
            continue
        if isinstance(rec, dict):
            yield rec


def load_records(path: str, dedup_runs: bool = False
                 ) -> List[Dict[str, Any]]:
    if dedup_runs:
        # the bench trajectory's reader already solves run_id dedup
        # (each run may emit several progressively richer lines; the
        # LAST one wins) — reuse it rather than fork the semantics
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench_compare import load_trajectory
        return load_trajectory(path)
    with open(path) as fh:
        return list(_parse_lines(fh))


def _match(rec: Dict[str, Any], events: Optional[List[str]],
           rank: Optional[int]) -> bool:
    if events and str(rec.get("event", "")) not in events:
        return False
    if rank is not None and rec.get("rank") != rank:
        return False
    return True


def format_record(rec: Dict[str, Any], t0: Optional[float] = None) -> str:
    """One human line: relative timestamp, rank, event, then the
    record's own fields in insertion order."""
    parts = []
    ts = rec.get("ts")
    if isinstance(ts, (int, float)):
        parts.append(f"+{ts - t0:9.3f}s" if t0 else
                     time.strftime("%H:%M:%S", time.localtime(ts)))
    for k in _HEAD_KEYS:
        if k in rec:
            parts.append(f"{k}={rec[k]}")
    for k, v in rec.items():
        if k in _HEAD_KEYS or k == "ts":
            continue
        if isinstance(v, float):
            v = round(v, 4)
        sv = json.dumps(v, separators=(",", ":"), default=str) \
            if isinstance(v, (dict, list)) else str(v)
        if len(sv) > 120:
            sv = sv[:117] + "..."
        parts.append(f"{k}={sv}")
    return "  ".join(parts)


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def summarize(records: List[Dict[str, Any]]) -> str:
    by_event: Dict[str, int] = {}
    ranks = set()
    iters: List[int] = []
    findings: List[Dict[str, Any]] = []
    ingest: List[Dict[str, Any]] = []
    cost: List[Dict[str, Any]] = []
    drift: List[Dict[str, Any]] = []
    fleet_access: List[Dict[str, Any]] = []
    bulk: List[Dict[str, Any]] = []
    alerts: List[Dict[str, Any]] = []
    roofs: List[Dict[str, Any]] = []
    for r in records:
        by_event[str(r.get("event", "?"))] = \
            by_event.get(str(r.get("event", "?")), 0) + 1
        if "rank" in r:
            ranks.add(r["rank"])
        if isinstance(r.get("iter"), int):
            iters.append(r["iter"])
        if r.get("event") in ("anomaly", "rank_divergence", "straggler",
                              "serve_batch_error", "recovery",
                              "drift_alert", "mapper_drift", "alert"):
            findings.append(r)
        if r.get("event") == "alert":
            alerts.append(r)
        if r.get("event") == "ingest":
            ingest.append(r)
        if r.get("event") == "cost_ledger":
            cost.append(r)
        if r.get("event") == "drift":
            drift.append(r)
        if r.get("event") == "serve_access" and "device" in r:
            fleet_access.append(r)
        if r.get("event") == "serve_bulk":
            bulk.append(r)
        if r.get("event") == "roofline":
            roofs.append(r)
    lines = [f"records: {len(records)}   ranks: {sorted(ranks)}"]
    if iters:
        lines.append(f"iterations: {min(iters)}..{max(iters)}")
    if cost:
        # one line each for the device-time ledger and the analytic
        # histogram plane it is checked against (obs/cost.py): means
        # over the drained batches, last achieved fraction
        flops = _mean([float(r.get("flops_per_iter", 0)) for r in cost])
        hbytes = _mean([float(r.get("hlo_bytes_per_iter", 0))
                        for r in cost])
        fracs = [float(r["achieved_fraction"]) for r in cost
                 if isinstance(r.get("achieved_fraction"), (int, float))]
        secs = [float(r["sec_per_iter"]) for r in cost
                if isinstance(r.get("sec_per_iter"), (int, float))]
        lines.append(
            f"cost: {len(cost)} ledger record(s)  "
            f"flops/iter={flops:.3e}  hlo_bytes/iter={hbytes:.3e}"
            + (f"  sec/iter={_mean(secs):.4g}" if secs else ""))
        hist_b = [float(r["hist_bytes_per_iter"]) for r in cost
                  if isinstance(r.get("hist_bytes_per_iter"),
                                (int, float))]
        if hist_b:
            lines.append(
                f"hist: analytic bytes/iter={_mean(hist_b):.3e}"
                + (f"  achieved_fraction={fracs[-1]:.4g} of HLO bytes"
                   if fracs else ""))
    if drift or by_event.get("drift_alert"):
        # one line for the drift & lineage plane (obs/drift.py): the
        # latest periodic evaluation's PSI vs the training profile,
        # the hysteresis-gated alert count, and resident model age
        last = drift[-1] if drift else {}
        parts = [f"drift: {len(drift)} evaluation(s)"]
        if isinstance(last.get("psi_max"), (int, float)):
            parts.append(f"psi_max={float(last['psi_max']):.4g}")
        if isinstance(last.get("score_psi"), (int, float)):
            parts.append(f"score_psi={float(last['score_psi']):.4g}")
        parts.append(f"alerts={by_event.get('drift_alert', 0)}")
        if isinstance(last.get("model_age_s"), (int, float)):
            parts.append(f"model_age_s={float(last['model_age_s']):.4g}")
        if by_event.get("drift_unavailable"):
            parts.append(f"unavailable={by_event['drift_unavailable']}")
        lines.append("  ".join(parts))
    if fleet_access or bulk or by_event.get("serve_spill"):
        # one line for the serving fleet (serve/ "Serving fleet"):
        # per-device request share from the device-attributed
        # serve_access records, queue-wait p95 across the fleet,
        # admission spills, and row-sharded bulk throughput
        parts = ["fleet:"]
        if fleet_access:
            per_dev: Dict[int, int] = {}
            for r in fleet_access:
                per_dev[int(r["device"])] = \
                    per_dev.get(int(r["device"]), 0) + 1
            total = sum(per_dev.values())
            share = " ".join(
                f"d{d}={100.0 * n / total:.0f}%"
                for d, n in sorted(per_dev.items()))
            parts.append(f"{total} request(s) [{share}]")
            waits = sorted(float(r["queue_ms"]) for r in fleet_access
                           if isinstance(r.get("queue_ms"),
                                         (int, float)))
            if waits:
                p95 = waits[min(len(waits) - 1,
                                int(0.95 * (len(waits) - 1) + 0.5))]
                parts.append(f"queue_p95_ms={p95:.4g}")
        parts.append(f"spills={by_event.get('serve_spill', 0)}")
        if bulk:
            rows = sum(int(r.get("rows", 0)) for r in bulk)
            rates = [float(r["rows_per_s"]) for r in bulk
                     if isinstance(r.get("rows_per_s"), (int, float))]
            parts.append(f"bulk_rows={rows}")
            if rates:
                parts.append(f"bulk_rows_per_s={_mean(rates):.4g}")
        lines.append("  ".join(parts))
    if alerts:
        # one line for the SLO plane (obs/slo.py): fire/resolve totals
        # and which objectives are still firing at the end of the
        # stream (last state per objective wins)
        fired = sum(1 for a in alerts if a.get("state") == "firing")
        resolved = sum(1 for a in alerts if a.get("state") == "resolved")
        last_state: Dict[str, str] = {}
        for a in alerts:
            last_state[str(a.get("objective", "?"))] = \
                str(a.get("state", "?"))
        active = sorted(o for o, s in last_state.items() if s == "firing")
        lines.append(
            f"alerts: fired={fired}  resolved={resolved}  "
            f"active={active if active else 'none'}")
    if roofs:
        # one line for the roofline plane (obs/kernelstats.py): the
        # latest parsed profile window's measured view — joined
        # executables, measured occupancy, the top kernel by device
        # time — plus the perfdb samples the stream appended
        last = roofs[-1]
        parts = [f"roofline: {len(roofs)} window(s)"]
        if isinstance(last.get("join_coverage"), (int, float)):
            parts.append(f"join={float(last['join_coverage']):.3f}")
        if isinstance(last.get("joined_executables"), int):
            parts.append(f"joined={last['joined_executables']}")
        if isinstance(last.get("measured_fraction"), (int, float)):
            parts.append(
                f"measured_fraction="
                f"{float(last['measured_fraction']):.4g}")
        if last.get("top_kernel"):
            parts.append(
                f"top_kernel={last['top_kernel']}"
                + (f"({float(last['top_kernel_us']):.4g}us)"
                   if isinstance(last.get("top_kernel_us"),
                                 (int, float)) else ""))
        if last.get("error"):
            parts.append(f"error={last['error']}")
        n_db = sum(int(r.get("samples", 0)) for r in records
                   if r.get("event") == "perfdb_append")
        if n_db:
            parts.append(f"perfdb_samples={n_db}")
        lines.append("  ".join(parts))
    if ingest:
        # one line per ingest (streamed/cached dataset build): source,
        # chunk arithmetic, the bounded-residency watermark, cache hit
        chunks = sum(int(r.get("chunks", 0)) for r in ingest)
        rows = sum(int(r.get("rows", 0)) for r in ingest)
        max_live = max(int(r.get("max_live_chunks", 0)) for r in ingest)
        hits = sum(1 for r in ingest if r.get("cache_hit"))
        srcs = sorted({str(r.get("source", "?")) for r in ingest})
        lines.append(
            f"ingest: {len(ingest)} dataset(s)  src={','.join(srcs)}  "
            f"chunks={chunks}  rows={rows}  max_live={max_live}  "
            f"cache_hits={hits}")
    lines.append("events:")
    for name, n in sorted(by_event.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {name:<24} {n}")
    if findings:
        lines.append(f"findings ({len(findings)}):")
        t0 = records[0].get("ts") if records else None
        for f in findings[-20:]:
            lines.append("  " + format_record(f, t0))
    return "\n".join(lines)


def _stat_id(path: str):
    """(st_dev, st_ino, st_size) of path, or None while it's absent
    (mid-rotation the new file may not exist yet)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_dev, st.st_ino, st.st_size)


def follow(path: str, events: Optional[List[str]],
           rank: Optional[int], _poll_s: float = 0.2) -> None:
    """tail -f semantics: print matching records as the writer appends
    (poll loop).  A readline() that races the writer mid-flush returns
    a newline-less fragment — buffer it and re-read until the line
    completes, so a large record split across flushes is parsed whole
    instead of dropped as two corrupt halves.

    Rotation-safe: on every idle poll the path is re-stat()ed — a new
    inode (rotate/rename) or a size smaller than our read offset
    (truncate-in-place) means the handle tails a dead offset, so the
    file is reopened from the start and the partial-fragment buffer is
    dropped with it (it belonged to the old stream)."""
    t0 = None
    partial = ""
    fh = open(path)
    try:
        while True:
            chunk = fh.readline()
            if not chunk:
                cur = _stat_id(path)
                if cur is not None:
                    opened = os.fstat(fh.fileno())
                    rotated = (cur[0], cur[1]) != (opened.st_dev,
                                                   opened.st_ino)
                    truncated = cur[2] < fh.tell()
                    if rotated or truncated:
                        fh.close()
                        fh = open(path)
                        partial = ""
                        continue
                time.sleep(_poll_s)
                continue
            partial += chunk
            if not partial.endswith("\n"):
                continue       # mid-flush fragment: wait for the rest
            line, partial = partial, ""
            for rec in _parse_lines([line]):
                if t0 is None and isinstance(rec.get("ts"), (int, float)):
                    t0 = rec["ts"]
                if _match(rec, events, rank):
                    print(format_record(rec, t0), flush=True)
    finally:
        fh.close()


def render_report(path: str) -> str:
    """Render a consolidated run report (obs/report.py markdown view)
    — the ``--report`` mode."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.obs.report import load_report, render_markdown
    return render_markdown(load_report(path))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=None,
                    help="telemetry JSONL file (or bench "
                         "trajectory with --dedup-runs)")
    ap.add_argument("--report", default=None, metavar="RUN_REPORT_JSON",
                    help="render a consolidated run_report.json "
                         "(run_report_out / GET /report) instead of "
                         "tailing a JSONL stream")
    ap.add_argument("--event", default="",
                    help="comma-separated event names to keep")
    ap.add_argument("--rank", type=int, default=None,
                    help="keep only this rank's records")
    ap.add_argument("--last", type=int, default=0,
                    help="only the last N matching records")
    ap.add_argument("--summary", action="store_true",
                    help="per-event counts + findings instead of lines")
    ap.add_argument("--follow", action="store_true",
                    help="keep reading as the file grows (Ctrl-C stops)")
    ap.add_argument("--dedup-runs", action="store_true",
                    help="dedup records by run_id (bench trajectory "
                         "semantics, reusing bench_compare's reader)")
    ap.add_argument("--json", action="store_true",
                    help="emit raw JSON lines instead of human format")
    args = ap.parse_args(argv)

    if args.report:
        print(render_report(args.report), end="")
        return 0
    if not args.path:
        ap.error("a JSONL path is required unless --report is given")

    events = [e for e in args.event.split(",") if e] or None
    if args.follow:
        try:
            follow(args.path, events, args.rank)
        except KeyboardInterrupt:
            pass
        return 0

    records = load_records(args.path, dedup_runs=args.dedup_runs)
    matched = [r for r in records if _match(r, events, args.rank)]
    if args.last > 0:
        matched = matched[-args.last:]
    if args.summary:
        print(summarize(matched))
        return 0
    t0 = None
    for rec in matched:
        if t0 is None and isinstance(rec.get("ts"), (int, float)):
            t0 = rec["ts"]
        print(json.dumps(rec, separators=(",", ":"), default=str)
              if args.json else format_record(rec, t0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
