"""Micro-benchmarks of the primitives that bound GBDT training on TPU.

Every op is chained N times inside ONE jit-compiled loop so the measurement
is device throughput, not dispatch/tunnel latency. Run on the real chip:

    python scripts/profile_micro.py
"""
from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import jax
import jax.numpy as jnp


def timeit(fn, *args, reps=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def chain(body, n):
    """Run body n times sequentially inside one jit (data-dependent)."""
    @jax.jit
    def run(*args):
        def step(i, carry):
            return body(i, carry, *args[1:])
        return jax.lax.fori_loop(0, n, step, args[0])
    return run


def main():
    R = 2_000_000
    F = 28
    Fp = 32
    B = 64
    N = 10
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, 63, size=(R, Fp)).astype(np.int32))
    bins_u8 = jnp.asarray(np.asarray(bins).astype(np.uint8))
    gh = jnp.asarray(rng.randn(R, 3).astype(np.float32))
    perm = jnp.asarray(rng.permutation(R).astype(np.int32))
    slot = jnp.asarray(rng.randint(0, 64, size=R).astype(np.int32))

    results = {}

    # 0. raw MXU throughput (chained, data-dependent)
    a = jnp.asarray(rng.randn(4096, 4096).astype(np.float32)).astype(
        jnp.bfloat16)
    f = chain(lambda i, x, a: (x @ a), N)
    t = timeit(f, a, a) / N
    results["matmul_4096_bf16_tflops"] = 2 * 4096**3 / t / 1e12

    # 1. HBM r/w bandwidth (chained adds)
    big = jnp.zeros((R, Fp), jnp.float32)
    f = chain(lambda i, x: x + 1.0, N)
    t = timeit(f, big) / N
    results["hbm_rw_f32_GBps"] = 2 * R * Fp * 4 / t / 1e9

    # 2. random row gather [R, Fp] uint8 (index fed by previous gather so
    # the chain cannot be elided)
    f = chain(lambda i, p, x: (p + x[p][:, 0].astype(jnp.int32)) % R, N)
    t = timeit(f, perm, bins_u8) / N
    results["row_gather_u8_ns_per_row"] = t / R * 1e9
    t = timeit(f, perm, bins) / N
    results["row_gather_i32_ns_per_row"] = t / R * 1e9

    # 2b. 1-D gather / scatter
    f = chain(lambda i, p, x: (p + x[p]) % R, N)
    t = timeit(f, perm, slot) / N
    results["gather_1d_ns_per_elem"] = t / R * 1e9
    f = chain(lambda i, p, x: (p + jnp.zeros_like(x).at[p].set(x)) % R, N)
    t = timeit(f, perm, slot) / N
    results["scatter_1d_unique_ns_per_elem"] = t / R * 1e9

    # 3. sort (key,payload)
    f = chain(lambda i, k, v: jax.lax.sort(((k * 7919 + 13) % R, v),
                                           num_keys=1)[0], N)
    t = timeit(f, slot, perm) / N
    results["sort_kv_2M_ms"] = t * 1e3

    # 4. cumsum
    f = chain(lambda i, x: jnp.cumsum(x) % 1000, N)
    t = timeit(f, slot) / N
    results["cumsum_2M_ms"] = t * 1e3

    # 5. current pallas histogram, jit-compiled, per-pass
    from lightgbm_tpu.ops.pallas_histogram import build_histograms_pallas_cm

    for S in (8, 64):
        @functools.partial(jax.jit, static_argnames=())
        def hist_loop(bins, gh, slot, _S=S):
            def step(i, acc):
                g, h, c = build_histograms_pallas_cm(
                    bins, gh, (slot + i) % _S, num_slots=_S, num_bins=B)
                return acc + g[0, 0, 0]
            return jax.lax.fori_loop(0, N, step, 0.0)
        t = timeit(hist_loop, bins, gh, slot) / N
        results[f"pallas_hist_S{S}_ms"] = t * 1e3

    for k, v in results.items():
        print(f"{k:36s} {v if isinstance(v, str) else round(v, 3)}")


if __name__ == "__main__":
    main()
