"""EFB uniform-stride padding waste measurement (VERDICT r4 item 8).

The reference stores bundles with jagged per-group offsets
(ref: src/io/dataset.cpp:108-176 — each FeatureGroup's bin range is
exactly the sum of its members' bins); the fused kernel's one-hot bin
extraction needs a UNIFORM per-column stride, so bundle columns are
padded to the widest and the adaptive cap (gbdt.py _setup_bundles)
tightens the bundle width only when padding would inflate storage >2x.

This script measures, for realistic feature-width mixes, what the
uniform padding actually costs relative to (a) the jagged ideal and
(b) the reference's uncapped bundling, plus how much bundling the cap
abandons. A per-column stride table (scalar-prefetched offsets into the
one-hot scratch) would recover the jagged layout on-chip — whether the
extra scalar loads beat the padded dot is the HARDWARE half of this
ablation (scripts/ablate_kernel.py territory, pending a live tunnel);
this half records the storage side either way.

Run: PYTHONPATH=/root/repo python scripts/ablate_efb_stride.py
"""
import numpy as np

from lightgbm_tpu.ops.efb import BundleLayout, find_bundles

RNG = np.random.RandomState(0)


def synth(kind, n=20000, F=200):
    """Sparse one-hot-ish feature sets with a given bin-width mix."""
    if kind == "uniform-small":        # OHE-style: all features 3 bins
        widths = np.full(F, 3)
    elif kind == "mixed":              # realistic: mostly small, a few wide
        widths = np.where(RNG.rand(F) < 0.9,
                          RNG.randint(2, 8, F), RNG.randint(64, 256, F))
    elif kind == "adversarial":        # the width mix the cap fears:
        widths = np.where(np.arange(F) % 10 == 0, 255, 2)
    else:
        raise ValueError(kind)
    # group features into near-exclusive cliques of ~10
    owner = RNG.randint(0, F // 10, n)
    masks = []
    for f in range(F):
        m = np.zeros(n, bool)
        m[owner == f // 10] = RNG.rand((owner == f // 10).sum()) < 0.9
        masks.append(m)
    return masks, [int(w) for w in widths]


def measure(kind):
    masks, widths = synth(kind)
    n = len(masks[0])
    F = len(masks)
    rows = []
    for cap_name, cap in (("uncapped(int16)", 32767),
                          ("8x max_bin(2040)", 2040),
                          ("4x max_bin(1020)", 1020)):
        bundles = find_bundles(masks, n, max_conflict_rate=1e-4,
                               max_bundle_bins=cap,
                               num_bin_per_feat=widths)
        col_widths = [1 + sum(widths[f] for f in b) for b in bundles]
        jagged = sum(col_widths)              # reference storage units
        padded = len(bundles) * max(col_widths) if bundles else 0
        rows.append((cap_name, len(bundles), jagged, padded,
                     padded / max(1, jagged)))
    print(f"\n== {kind}: F={F}, widths min/med/max = "
          f"{min(widths)}/{int(np.median(widths))}/{max(widths)}")
    print(f"{'cap':>18} {'cols':>6} {'jagged':>8} {'padded':>8} "
          f"{'pad/jag':>8}")
    for r in rows:
        print(f"{r[0]:>18} {r[1]:>6} {r[2]:>8} {r[3]:>8} {r[4]:>8.2f}")
    return rows


if __name__ == "__main__":
    for kind in ("uniform-small", "mixed", "adversarial"):
        measure(kind)
    print("\n(adaptive cap keeps the first row whose pad/jag <= 2.0 — "
          "gbdt.py _setup_bundles)")
