#!/bin/bash
# Build the reference LightGBM (/root/reference, read-only) out-of-tree so
# parity fixtures can be (re)generated with scripts/gen_parity_fixtures.py.
#
# The reference's external_libs/ submodules are empty in this image:
#  - fmt: taken from TensorFlow's bundled spdlog copy (same namespace/API)
#  - fast_double_parser: minimal strtod-backed stand-in (identical API;
#    only used by the reference's text parser, not by anything we compare)
#  - eigen: TensorFlow's bundled Eigen (needs -std=c++17, the only flag
#    change vs the reference's default build)
#
# Produces an importable package at /tmp/refpkg:
#   python -c "import sys; sys.path.insert(0, '/tmp/refpkg'); import lightgbm"
set -e
rm -rf /tmp/refsrc /tmp/refbuild
cp -r /root/reference /tmp/refsrc
chmod -R u+w /tmp/refsrc
SPDLOG_FMT=/opt/venv/lib/python3.12/site-packages/tensorflow/include/external/spdlog/include/spdlog/fmt/bundled
mkdir -p /tmp/refsrc/external_libs/fmt/include/fmt
cp "$SPDLOG_FMT"/*.h /tmp/refsrc/external_libs/fmt/include/fmt/
mkdir -p /tmp/refsrc/external_libs/fast_double_parser/include
cat > /tmp/refsrc/external_libs/fast_double_parser/include/fast_double_parser.h <<'HDR'
// minimal strtod-backed stand-in for fast_double_parser (API-compatible)
#pragma once
#include <cstdlib>
namespace fast_double_parser {
inline const char* parse_number(const char* p, double* out) {
  char* end = nullptr;
  *out = std::strtod(p, &end);
  if (end == p) return nullptr;
  return end;
}
}  // namespace fast_double_parser
HDR
rm -rf /tmp/refsrc/external_libs/eigen
ln -s /opt/venv/lib/python3.12/site-packages/tensorflow/include \
    /tmp/refsrc/external_libs/eigen
cd /tmp/refsrc
cmake -B /tmp/refbuild -S . -DCMAKE_BUILD_TYPE=Release \
    -DBUILD_STATIC_LIB=OFF -DCMAKE_CXX_STANDARD=17 \
    -DCMAKE_CXX_FLAGS="-std=gnu++17" > /tmp/refcmake.log 2>&1
cmake --build /tmp/refbuild -j16 >> /tmp/refcmake.log 2>&1
cp /tmp/refsrc/lib_lightgbm.so /tmp/refsrc/python-package/lightgbm/
mkdir -p /tmp/refpkg
ln -sfn /tmp/refsrc/python-package/lightgbm /tmp/refpkg/lightgbm
echo "reference built: import via sys.path.insert(0, '/tmp/refpkg')"
