"""One-hot build strategy shootout for the fused level kernel.

The current build materialises jnp.repeat(bins_i32, B) — an [FB, C] i32
intermediate (~84 GB of VMEM traffic per pass at 10.5M rows) before the
compare. Variants tried here:
  A: current (bulk repeat + iota compare)
  B: per-feature unrolled loop (no repeated i32 intermediate)
  C: MXU broadcast (repeat matrix @ bins_bf16, compare in f32)
  D: current with tile_rows=2048
Each runs the FULL level kernel (build + routing + hist dots) so wins
here translate directly. Run: ROWS=10500000 python scripts/ablate_build.py
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lightgbm_tpu.ops import fused_level as fl


def make_kernel(build: str, B, F_oh, Sp, nch):
    def kernel(bins_ref, leaf_ref, gh_ref, w_ref, tbl_ref,
               hist_ref, newleaf_ref, oh_ref, *, rep_ref=None):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            hist_ref[:] = jnp.zeros_like(hist_ref)

        C = bins_ref.shape[1]
        FB = F_oh * B

        if build == "A":
            bins_val = bins_ref[:].astype(jnp.int32)
            big = jnp.repeat(bins_val[:F_oh], B, axis=0)
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (FB, C), 0) % B
            oh_ref[:] = (big == iota_b).astype(jnp.bfloat16)
        elif build == "B":
            iota = jax.lax.broadcasted_iota(jnp.int32, (B, C), 0)
            bins_val = bins_ref[:].astype(jnp.int32)
            for f in range(F_oh):
                bf = jnp.broadcast_to(bins_val[f:f + 1, :], (B, C))
                oh_ref[f * B:(f + 1) * B, :] = (bf == iota).astype(
                    jnp.bfloat16)
        elif build == "C":
            bins_bf = bins_ref[:F_oh].astype(jnp.bfloat16)      # [F, C]
            big = jax.lax.dot_general(
                rep_ref[:], bins_bf, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)              # [FB, C]
            iota_b = (jax.lax.broadcasted_iota(jnp.int32, (FB, C), 0)
                      % B).astype(jnp.float32)
            oh_ref[:] = (big == iota_b).astype(jnp.bfloat16)

        leafb = leaf_ref[:]
        oh = oh_ref[:]
        D = jax.lax.dot_general(w_ref[:], oh, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        left_i = (D > 0.5).astype(jnp.int32)
        leaf_of_slot = tbl_ref[:, 0:1]
        right_delta = tbl_ref[:, 1:2]
        small_left_i = (tbl_ref[:, 2:3] > 0).astype(jnp.int32)
        P_i = (jnp.broadcast_to(leafb, (Sp, C))
               == leaf_of_slot).astype(jnp.int32)
        same_i = 1 - jnp.bitwise_xor(left_i, small_left_i)
        in_small = (P_i * same_i).astype(jnp.bfloat16)
        chans = []
        for ch in range(nch):
            g = gh_ref[ch:ch + 1, :]
            chans.append(in_small * jnp.broadcast_to(g, (Sp, C)))
        ghs = jnp.concatenate(chans, axis=0)
        hist_ref[:] += jax.lax.dot_general(
            oh, ghs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        go_right = P_i * (1 - left_i)
        delta = jnp.sum(go_right * jnp.broadcast_to(right_delta, (Sp, C)),
                        axis=0, keepdims=True)
        newleaf_ref[:] = leafb + delta
    return kernel


@functools.partial(jax.jit, static_argnames=("build", "B", "F_oh", "Sp",
                                             "nch", "C"))
def level_pass_variant(bins_T, leaf_T, gh_T, W, tbl, rep, *, build, B,
                       F_oh, Sp, nch, C):
    Fp, R = bins_T.shape
    FB = F_oh * B
    T = R // C
    kern = make_kernel(build, B, F_oh, Sp, nch)
    in_specs = [
        pl.BlockSpec((Fp, C), lambda t: (0, t)),
        pl.BlockSpec((1, C), lambda t: (0, t)),
        pl.BlockSpec((8, C), lambda t: (0, t)),
        pl.BlockSpec((Sp, FB), lambda t: (0, 0)),
        pl.BlockSpec((Sp, 128), lambda t: (0, 0)),
    ]
    args = [bins_T, leaf_T, gh_T, W, tbl]
    if build == "C":
        kern0 = kern

        def kern_c(bins_ref, leaf_ref, gh_ref, w_ref, tbl_ref, rep_ref,
                   hist_ref, newleaf_ref, oh_ref):
            kern0(bins_ref, leaf_ref, gh_ref, w_ref, tbl_ref,
                  hist_ref, newleaf_ref, oh_ref, rep_ref=rep_ref)
        kern = kern_c
        in_specs.append(pl.BlockSpec((FB, Fp), lambda t: (0, 0)))
        args.append(rep)
    hist, nl = pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((FB, nch * Sp), lambda t: (0, 0)),
            pl.BlockSpec((1, C), lambda t: (0, t)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((FB, nch * Sp), jnp.float32),
            jax.ShapeDtypeStruct((1, R), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((FB, C), jnp.bfloat16)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(*args)
    return hist, nl


def main():
    R = int(os.environ.get("ROWS", 10_500_000))
    reps = int(os.environ.get("REPS", 3))
    F, B = fl.feature_layout(28, 63)
    Fp = max(F, 8)
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(R).astype(np.float32))
    ones = jnp.ones((R,), jnp.float32)
    rep_np = np.zeros((F * B, Fp), np.float32)
    for f in range(F):
        rep_np[f * B:(f + 1) * B, f] = 1
    rep = jnp.asarray(rep_np, jnp.bfloat16)

    ref_hist = None
    for build, C in [("A", 1024), ("B", 1024), ("C", 1024),
                     ("A", 2048), ("B", 2048)]:
        Rp = ((R + C - 1) // C) * C
        bins_T = jnp.asarray(
            rng.randint(0, 63, size=(Fp, Rp)).astype(np.int8))
        leaf_T = jnp.where(jnp.arange(Rp)[None, :] < R, 0, -1).astype(
            jnp.int32)
        gh_T = fl.pack_gh(jnp.pad(g, (0, Rp - R)),
                          jnp.pad(ones, (0, Rp - R)),
                          jnp.pad(ones, (0, Rp - R)), 5)
        for Sp in (8, 128):
            W = jnp.zeros((Sp, F * B), jnp.bfloat16).at[0, :B].set(1)
            tbl = (jnp.zeros((Sp, 128), jnp.int32)
                   .at[:, 0].set(-2).at[0, 0].set(0).at[0, 2].set(1))
            try:
                def one(lt):
                    return level_pass_variant(
                        bins_T, lt, gh_T, W, tbl, rep, build=build, B=B,
                        F_oh=F, Sp=Sp, nch=5, C=C)
                h, nl = one(leaf_T)
                s = float(jnp.sum(h))
                t0 = time.perf_counter()
                lt = leaf_T
                for _ in range(reps):
                    h, lt = one(lt)
                float(jnp.sum(h))
                dt = (time.perf_counter() - t0) / reps
                print(f"  build={build} C={C} Sp={Sp:4d}"
                      f"  {dt*1e3:8.1f} ms/pass  (sum={s:.1f})")
            except Exception as e:
                print(f"  build={build} C={C} Sp={Sp:4d}  FAILED "
                      f"{type(e).__name__}: {str(e)[:160]}")


if __name__ == "__main__":
    main()
