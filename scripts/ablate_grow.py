"""Amortized ablation timing of grow_tree_fused on the attached chip.

Times N back-to-back grows with ONE final block (dispatch pipelining stays
intact, matching how training actually runs).
Run: BENCH_ROWS=2000000 python scripts/ablate_grow.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.models.frontier2 import grow_tree_fused
from lightgbm_tpu.ops.fused_level import pack_gh


def main():
    n = int(os.environ.get("BENCH_ROWS", 2_000_000))
    reps = int(os.environ.get("REPS", 5))
    rng = np.random.RandomState(0)
    X = rng.rand(n, 28).astype(np.float32)
    w = rng.randn(28).astype(np.float32)
    y = (X @ w + 0.5 * rng.randn(n) > 0).astype(np.float32)
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 1,
              "min_sum_hessian_in_leaf": 1e-3, "verbose": -1,
              "metric": "None", "tpu_engine": "fused"}
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63, "verbose": -1})
    booster = lgb.Booster(params=params, train_set=ds)
    booster.update()
    g = booster._gbdt

    grad, hess = g._get_gradients()
    pad = g.fused_Rp - g.num_data
    fm_pad = jnp.ones((g.fused_f_oh,), bool).at[28:].set(False)

    def run(nch, extra_levels, leaves):
        gh_T = pack_gh(jnp.pad(grad[0], (0, pad)), jnp.pad(hess[0], (0, pad)),
                       jnp.pad(jnp.ones_like(grad[0]), (0, pad)), nch)
        def one():
            return grow_tree_fused(
                g.fused_bins_T, gh_T, g.fused_meta, fm_pad, g.params,
                leaves, g.fused_Bp, g.fused_f_oh, num_rows=g.num_data,
                nch=nch, max_depth=-1, extra_levels=extra_levels)
        t_, rl = one()  # compile
        jax.block_until_ready(rl)
        t0 = time.perf_counter()
        outs = [one() for _ in range(reps)]
        for t_, rl in outs:
            pass
        jax.block_until_ready(outs[-1][1])
        jax.block_until_ready(outs[-1][0].num_leaves)
        dt = (time.perf_counter() - t0) / reps
        print(f"  nch={nch} extras={extra_levels} leaves={leaves:4d}"
              f"  {dt*1e3:8.1f} ms/tree  (num_leaves="
              f"{int(outs[-1][0].num_leaves)})")

    print(f"rows={n} reps={reps}")
    run(5, 3, 255)
    run(5, 0, 255)
    run(3, 3, 255)
    run(3, 0, 255)
    run(5, 3, 63)
    run(5, 0, 63)


if __name__ == "__main__":
    main()
